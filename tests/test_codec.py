"""Wire codec roundtrips for all peer payloads.

Layouts mirror the reference's speedy encodings (see codec.py docstring);
roundtrip + structural fixtures here, cross-impl byte fixtures would need a
Rust toolchain (absent) so we lock the layout with golden bytes instead.
"""

import pytest

from corrosion_tpu.types.actor import ActorId, ClusterId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import (
    Change,
    ChangeV1,
    ChangesetEmpty,
    ChangesetEmptySet,
    ChangesetFull,
)
from corrosion_tpu.types.codec import (
    NeedEmpty,
    NeedFull,
    NeedPartial,
    SyncRejection,
    SyncState,
    decode_bi_payload,
    decode_sync_msg,
    decode_uni_payload,
    deframe,
    encode_bi_payload_sync_start,
    encode_sync_msg,
    encode_uni_payload,
    frame,
    SyncTraceContext,
)


def mk_change(**kw):
    base = dict(
        table="tests",
        pk=b"\x01\x09\x01",
        cid="text",
        val="hello",
        col_version=1,
        db_version=7,
        seq=0,
        site_id=b"\x11" * 16,
        cl=1,
    )
    base.update(kw)
    return Change(**base)


def test_uni_payload_roundtrip():
    cv = ChangeV1(
        actor_id=ActorId(b"\x22" * 16),
        changeset=ChangesetFull(
            version=7,
            changes=(mk_change(), mk_change(cid="num", val=42, seq=1)),
            seqs=(0, 1),
            last_seq=1,
            ts=Timestamp(123456789),
        ),
    )
    data = encode_uni_payload(cv, ClusterId(3))
    out, cluster = decode_uni_payload(data)
    assert cluster == ClusterId(3)
    assert out == cv


def test_uni_payload_default_on_eof_cluster_id():
    cv = ChangeV1(
        actor_id=ActorId(b"\x22" * 16),
        changeset=ChangesetEmpty(versions=(1, 5), ts=None),
    )
    data = encode_uni_payload(cv, ClusterId(0))
    # strip trailing u16 cluster id; decoder must default it (speedy
    # #[speedy(default_on_eof)])
    out, cluster = decode_uni_payload(data[:-2])
    assert cluster == ClusterId(0)
    assert out == cv


def test_changeset_variants_roundtrip():
    for cs in [
        ChangesetEmpty(versions=(2, 9), ts=Timestamp(5)),
        ChangesetEmpty(versions=(2, 9), ts=None),
        ChangesetEmptySet(versions=((1, 2), (5, 5)), ts=Timestamp(9)),
        ChangesetFull(
            version=1,
            changes=(mk_change(val=None), mk_change(val=2.5), mk_change(val=b"\x00")),
            seqs=(0, 2),
            last_seq=10,
            ts=Timestamp(1),
        ),
    ]:
        cv = ChangeV1(actor_id=ActorId(b"\x01" * 16), changeset=cs)
        out, _ = decode_uni_payload(encode_uni_payload(cv))
        assert out == cv


def test_bi_payload_roundtrip():
    aid = ActorId.new_random()
    data = encode_bi_payload_sync_start(
        aid, SyncTraceContext(traceparent="00-abc-def-01"), ClusterId(1)
    )
    out_aid, trace, cluster = decode_bi_payload(data)
    assert out_aid == aid
    assert trace.traceparent == "00-abc-def-01"
    assert trace.tracestate is None
    assert cluster == ClusterId(1)


def test_sync_state_roundtrip():
    a1, a2 = ActorId(b"\x01" * 16), ActorId(b"\x02" * 16)
    st = SyncState(
        actor_id=a1,
        heads={a1: 10, a2: 20},
        need={a2: [(1, 3), (7, 7)]},
        partial_need={a2: {9: [(0, 4), (6, 6)]}},
        last_cleared_ts=Timestamp(77),
    )
    out = decode_sync_msg(encode_sync_msg(st))
    assert out.actor_id == a1
    assert out.heads == st.heads
    assert out.need == st.need
    assert out.partial_need == st.partial_need
    assert out.last_cleared_ts == st.last_cleared_ts


def test_sync_msg_variants():
    cv = ChangeV1(
        actor_id=ActorId(b"\x03" * 16),
        changeset=ChangesetEmpty(versions=(1, 1), ts=None),
    )
    assert decode_sync_msg(encode_sync_msg(cv)) == cv
    assert decode_sync_msg(encode_sync_msg(Timestamp(42))) == Timestamp(42)
    rej = SyncRejection(SyncRejection.DIFFERENT_CLUSTER)
    assert decode_sync_msg(encode_sync_msg(rej)) == rej
    req = [
        (
            ActorId(b"\x04" * 16),
            [
                NeedFull((1, 5)),
                NeedPartial(version=7, seqs=((0, 2), (5, 9))),
                NeedEmpty(ts=Timestamp(3)),
                NeedEmpty(ts=None),
            ],
        )
    ]
    assert decode_sync_msg(encode_sync_msg(req)) == req


def test_golden_bytes_empty_changeset():
    # Locks the layout: UniPayload tags (3×u32 LE zeros), actor uuid,
    # Changeset::Empty tag u8=0, start/end u64 LE, Option ts u8=0, cluster u16.
    cv = ChangeV1(
        actor_id=ActorId(b"\xaa" * 16),
        changeset=ChangesetEmpty(versions=(1, 2), ts=None),
    )
    data = encode_uni_payload(cv, ClusterId(0))
    expect = (
        b"\x00\x00\x00\x00" * 3
        + b"\xaa" * 16
        + b"\x00"
        + (1).to_bytes(8, "little")
        + (2).to_bytes(8, "little")
        + b"\x00"
        + b"\x00\x00"
    )
    assert data == expect


def test_framing():
    p = b"hello world"
    buf = frame(p) + frame(b"")
    got1, pos = deframe(buf)
    assert got1 == p
    got2, pos = deframe(buf, pos)
    assert got2 == b""
    got3, pos2 = deframe(buf, pos)
    assert got3 is None and pos2 == pos


def test_framing_partial():
    p = frame(b"abcdef")
    got, pos = deframe(p[:5])
    assert got is None


def test_change_estimated_size():
    c = mk_change()
    assert c.estimated_byte_size() > 0


@pytest.mark.parametrize("bad", [b"", b"\x01\x00\x00\x00"])
def test_decode_garbage_raises(bad):
    with pytest.raises(Exception):
        decode_uni_payload(bad)


def test_randomized_uni_roundtrip_fuzz():
    """Randomized encode->decode over the full value/type space of a
    Change (int64 extremes, floats incl. inf, unicode, blobs, NULL,
    empty/long strings) and every changeset variant — the structural
    fixtures above lock the layout, this locks the codec against
    edge-value length/sign handling."""
    import random

    from corrosion_tpu.types.pack import pack_columns

    rng = random.Random(777)

    def rand_val():
        return rng.choice(
            [
                None,
                0,
                1,
                -1,
                2**63 - 1,
                -(2**63),
                0.0,
                -1.5,
                float("inf"),
                1e308,
                "",
                "x" * rng.randint(1, 300),
                "é中 end",
                b"",
                bytes(rng.randbytes(rng.randint(1, 64))),
            ]
        )

    def rand_change():
        # NB: no per-change ts — the wire unit carries 9 fields like the
        # reference's Change (change.rs:19); ts rides at changeset level
        return mk_change(
            table=rng.choice(["tests", "t2", "a" * 40]),
            pk=pack_columns([rng.randint(-(2**40), 2**40)]),
            cid=rng.choice(["text", "-1", "c" * 30]),
            val=rand_val(),
            col_version=rng.randint(1, 2**31),
            db_version=rng.randint(1, 2**50),
            seq=rng.randint(0, 2**20),
            site_id=rng.randbytes(16),
            cl=rng.randint(1, 2**30),
        )

    aid = ActorId.new_random()
    for trial in range(200):
        kind = rng.randrange(3)
        if kind == 0:
            changes = tuple(rand_change() for _ in range(rng.randint(0, 6)))
            seqs = (0, max(0, len(changes) - 1))
            cs = ChangesetFull(
                version=rng.randint(1, 2**40),
                changes=changes,
                seqs=seqs,
                last_seq=seqs[1],
                ts=Timestamp(rng.randint(0, 2**60)),
            )
        elif kind == 1:
            cs = ChangesetEmpty(
                versions=(1, rng.randint(1, 2**30)),
                ts=Timestamp(rng.randint(0, 2**60)),
            )
        else:
            starts = sorted(rng.randint(1, 2**30) for _ in range(3))
            cs = ChangesetEmptySet(
                versions=tuple(
                    (s, s + rng.randint(0, 100)) for s in starts
                ),
                ts=Timestamp(rng.randint(0, 2**60)),
            )
        cv = ChangeV1(actor_id=aid, changeset=cs)
        out, _cluster = decode_uni_payload(encode_uni_payload(cv))
        assert out == cv, f"trial {trial}: {cv!r} != {out!r}"


# -- r11 envelope ext: origin wall stamp + traceparent ----------------------


def _stamped_cv(**ext):
    return ChangeV1(
        actor_id=ActorId(b"\x22" * 16),
        changeset=ChangesetFull(
            version=7,
            changes=(mk_change(),),
            seqs=(0, 0),
            last_seq=0,
            ts=Timestamp(11),
        ),
        **ext,
    )


def test_envelope_ext_roundtrip_uni_and_sync():
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    cv = _stamped_cv(origin_ts=1722800000.125, traceparent=tp)
    out, cid = decode_uni_payload(encode_uni_payload(cv, ClusterId(5)))
    assert cid == ClusterId(5)
    assert out.origin_ts == pytest.approx(1722800000.125)
    assert out.traceparent == tp
    assert out == cv  # ext fields are compare=False: identity unchanged

    got = decode_sync_msg(encode_sync_msg(cv))
    assert got.origin_ts == pytest.approx(1722800000.125)
    assert got.traceparent == tp

    # each stamp travels independently
    only_ts = _stamped_cv(origin_ts=2.5)
    out2, _ = decode_uni_payload(encode_uni_payload(only_ts))
    assert out2.origin_ts == pytest.approx(2.5)
    assert out2.traceparent is None


def test_envelope_ext_old_new_compat():
    """Both directions of the version-gate: an unstamped (old-layout)
    payload decodes on a new peer with empty ext, a NEW stamped payload
    decodes on an OLD peer (which stops reading at cluster_id and
    ignores the trailing ext — the same default_on_eof tolerance the
    cluster_id field itself relies on)."""
    from corrosion_tpu.types.codec import Reader, read_change_v1

    plain = _stamped_cv()
    stamped = _stamped_cv(
        origin_ts=123.5, traceparent="00-" + "11" * 16 + "-" + "22" * 8 + "-01"
    )

    # old payload → new decoder: unstamped bytes are byte-identical to
    # the pre-r11 layout (the ext block is only written when non-empty)
    data_old = encode_uni_payload(plain, ClusterId(1))
    out, cid = decode_uni_payload(data_old)
    assert (out.origin_ts, out.traceparent) == (None, None)
    assert cid == ClusterId(1)
    data_new = encode_uni_payload(stamped, ClusterId(1))
    assert len(data_new) > len(data_old)
    assert data_new[: len(data_old)] == data_old  # strictly trailing ext

    # new payload → OLD decoder (emulated pre-r11 read path)
    r = Reader(data_new)
    # UniPayload::V1 / UniPayloadV1::Broadcast / BroadcastV1::Change
    assert (r.u32(), r.u32(), r.u32()) == (0, 0, 0)
    old_cv = read_change_v1(r)
    old_cid = ClusterId(r.u16())
    assert old_cv == plain
    assert old_cid == ClusterId(1)
    assert not r.eof()  # the ext bytes are simply left unread

    # same property on the sync wire
    sync_old = encode_sync_msg(plain)
    sync_new = encode_sync_msg(stamped)
    assert sync_new[: len(sync_old)] == sync_old
    assert decode_sync_msg(sync_old).origin_ts is None


# -- r12 envelope ext v2 + SWIM trailing ext: telemetry digests -------------


def test_envelope_ext_v2_digest_compat():
    """Both directions of the r12 digest gate on the broadcast
    envelope: digest-free payloads stay byte-identical to the r11
    layout (v2 is only written when a digest rides along), and an
    emulated r11 reader parses a digest-carrying v2 payload — it reads
    the version byte (2 passes its `>= v1` gate), the two optional
    stamps, and leaves the digest bytes unread."""
    from corrosion_tpu.types.codec import (
        Reader,
        decode_uni_payload_ext,
        read_change_v1,
    )

    dig = b"\x01" + b"opaque-digest-bytes" * 3
    plain = _stamped_cv()
    stamped = _stamped_cv(origin_ts=99.25)

    # digest-free bytes: the digest kwarg existing changes nothing
    assert encode_uni_payload(plain, ClusterId(1), digest=None) == (
        encode_uni_payload(plain, ClusterId(1))
    )
    base = encode_uni_payload(stamped, ClusterId(1))
    with_dig = encode_uni_payload(stamped, ClusterId(1), digest=dig)
    assert len(with_dig) > len(base)

    # new payload → new reader: the digest surfaces
    cv, cid, got = decode_uni_payload_ext(with_dig)
    assert got == dig
    assert cid == ClusterId(1)
    assert cv.origin_ts == pytest.approx(99.25)
    # ...and the digest-less decode of the SAME bytes ignores it
    cv2, _ = decode_uni_payload(with_dig)
    assert cv2 == stamped

    # digest-free payload → new reader: no digest
    assert decode_uni_payload_ext(base)[2] is None

    # new payload → OLD (r11) reader: emulated v1 ext read path
    r = Reader(with_dig)
    assert (r.u32(), r.u32(), r.u32()) == (0, 0, 0)
    old_cv = read_change_v1(r)
    assert ClusterId(r.u16()) == ClusterId(1)
    assert r.u8() >= 1  # r11 gate: `< v1` is the only rejection
    assert r.opt(r.f64) == pytest.approx(99.25)  # origin_ts
    assert r.opt(r.string) is None  # traceparent
    assert old_cv == stamped
    assert not r.eof()  # digest vec left unread, exactly like r11 would

    # a digest can ride a fully UNSTAMPED change too (the broadcast
    # loop offers the ext regardless of stamps)
    only_dig = encode_uni_payload(plain, ClusterId(1), digest=dig)
    cv3, _, got3 = decode_uni_payload_ext(only_dig)
    assert got3 == dig and cv3 == plain and cv3.origin_ts is None


# -- r19 envelope ext v3: tail-sampling trace meta ---------------------------


def test_envelope_ext_v3_trace_meta_compat():
    """Both directions of the r19 trace-meta gate: meta-free payloads
    stay byte-identical to the r11/r12 layouts (v3 is only written when
    meta rides along), an emulated PRE-V3 reader over a v3 payload reads
    the stamps + the (empty) digest vec and leaves the trailing meta
    byte-exactly unread, and a V3 reader over a v1/v2 body hits eof and
    yields no trace meta."""
    from corrosion_tpu.runtime.trace import (
        bump_hop,
        make_meta,
        meta_forced,
        meta_hop,
    )
    from corrosion_tpu.types.codec import (
        Reader,
        decode_uni_payload_ext,
        read_change_v1,
    )

    meta = make_meta(forced=True, hop=2)
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    stamped = _stamped_cv(origin_ts=7.5, traceparent=tp)
    with_meta = _stamped_cv(origin_ts=7.5, traceparent=tp, trace_meta=meta)

    # meta-free bytes: the field existing changes nothing (v1 layout)
    v1_bytes = encode_uni_payload(stamped, ClusterId(1))
    assert encode_uni_payload(
        _stamped_cv(origin_ts=7.5, traceparent=tp, trace_meta=None),
        ClusterId(1),
    ) == v1_bytes

    # new payload → new reader: meta surfaces, flags/hop decode
    v3_bytes = encode_uni_payload(with_meta, ClusterId(1))
    assert len(v3_bytes) > len(v1_bytes)
    cv, cid, dig = decode_uni_payload_ext(v3_bytes)
    assert cid == ClusterId(1)
    assert cv.trace_meta == meta
    assert meta_forced(cv.trace_meta) and meta_hop(cv.trace_meta) == 2
    assert dig is None  # the v3 padding vec is normalized, never b""
    assert cv.origin_ts == pytest.approx(7.5)
    assert cv.traceparent == tp

    # v3 reader over a V1 body: no trace meta (eof before the gate)
    assert decode_uni_payload_ext(v1_bytes)[0].trace_meta is None
    # ...and over a V2 (digest-carrying) body: digest intact, meta None
    v2_bytes = encode_uni_payload(stamped, ClusterId(1), digest=b"\x01dd")
    cv2, _, dig2 = decode_uni_payload_ext(v2_bytes)
    assert dig2 == b"\x01dd" and cv2.trace_meta is None

    # new payload → emulated PRE-V3 (r12) reader: version byte passes
    # its >= v1 gate, stamps read, digest vec read (empty), and exactly
    # the trailing opt<u8> meta (2 bytes) is left unread
    r = Reader(v3_bytes)
    assert (r.u32(), r.u32(), r.u32()) == (0, 0, 0)
    old_cv = read_change_v1(r)
    assert ClusterId(r.u16()) == ClusterId(1)
    assert r.u8() >= 2  # r12 gate: digest vec is read for ver >= 2
    assert r.opt(r.f64) == pytest.approx(7.5)
    assert r.opt(r.string) == tp
    assert r.vec_u8() == b""  # the meta-only payload's padding vec
    assert len(v3_bytes) - r.pos == 2  # opt-present byte + meta byte
    assert old_cv == stamped

    # digest + meta ride together (the broadcast loop's re-written ext)
    both = encode_uni_payload(with_meta, ClusterId(1), digest=b"\x01dd")
    cv3, _, dig3 = decode_uni_payload_ext(both)
    assert dig3 == b"\x01dd" and cv3.trace_meta == meta

    # same gate on the sync wire
    got = decode_sync_msg(encode_sync_msg(with_meta))
    assert got.trace_meta == meta
    assert decode_sync_msg(encode_sync_msg(stamped)).trace_meta is None

    # hop bump saturates and preserves flags (the relay path helper)
    assert meta_hop(bump_hop(meta)) == 3 and meta_forced(bump_hop(meta))
    assert meta_hop(bump_hop(make_meta(hop=63))) == 63


def test_snapshot_req_traceparent_compat():
    """The r19 trailing traceparent on SnapshotReq: absent → r17 bytes
    unchanged (an r17 server consumes the whole frame), present → a
    strict trailing extension an r17 reader never reaches, and the r19
    reader over an r17 frame yields None."""
    from corrosion_tpu.types.codec import (
        Reader,
        SnapshotReq,
        decode_bi_payload_any,
        encode_bi_payload_snapshot_req,
    )

    aid = ActorId(b"\x41" * 16)
    plain = SnapshotReq(actor_id=aid, schema_sha=b"s" * 8, cluster_id=ClusterId(2))
    tp = "00-" + "ee" * 16 + "-" + "ff" * 8 + "-01"
    traced = SnapshotReq(
        actor_id=aid, schema_sha=b"s" * 8, cluster_id=ClusterId(2),
        traceparent=tp,
    )

    plain_bytes = encode_bi_payload_snapshot_req(plain)
    traced_bytes = encode_bi_payload_snapshot_req(traced)
    assert traced_bytes[: len(plain_bytes)] == plain_bytes  # strictly trailing

    kind, req = decode_bi_payload_any(traced_bytes)
    assert kind == "snapshot" and req.traceparent == tp
    kind2, req2 = decode_bi_payload_any(plain_bytes)
    assert kind2 == "snapshot" and req2.traceparent is None

    # emulated r17 reader on the traced frame: stops after cluster_id,
    # the traceparent bytes are simply left unread
    r = Reader(traced_bytes)
    assert (r.u32(), r.u32()) == (0, 1)
    assert ActorId(r.raw(16)) == aid
    assert r.vec_u8() == b"s" * 8
    assert ClusterId(r.u16()) == ClusterId(2)
    assert not r.eof()
    # ...and consumes the plain frame whole
    r2 = Reader(plain_bytes)
    r2.u32(), r2.u32(), r2.raw(16), r2.vec_u8(), r2.u16()
    assert r2.eof()


def test_swim_digest_ext_compat():
    """Same discipline on the gossip datagrams: a digest-free SWIM
    packet encodes zero ext bytes (an emulated pre-r12 decoder consumes
    the WHOLE packet), and a digest-carrying packet is a strict trailing
    extension the old decoder never reaches."""
    from corrosion_tpu.net.gossip_codec import (
        MemberState,
        MemberUpdate,
        MsgKind,
        SwimMessage,
        decode_swim,
        encode_swim,
        read_actor,
    )
    from corrosion_tpu.types.actor import Actor
    from corrosion_tpu.types.codec import Reader

    a = Actor(id=ActorId(b"\x31" * 16), addr="a:1", ts=Timestamp(3))
    b = Actor(id=ActorId(b"\x32" * 16), addr="b:2", ts=Timestamp(4))
    msg = SwimMessage(
        kind=MsgKind.PING,
        probe_no=9,
        sender=a,
        updates=[MemberUpdate(b, 2, MemberState.ALIVE)],
    )
    plain_bytes = encode_swim(msg)
    msg.digest = b"\x01tiny-digest"
    dig_bytes = encode_swim(msg)

    # strict trailing extension of the byte-identical digest-free packet
    assert dig_bytes[: len(plain_bytes)] == plain_bytes
    assert len(dig_bytes) > len(plain_bytes)

    # new decoder: digest surfaces on the ext'd packet, None otherwise
    assert decode_swim(dig_bytes).digest == msg.digest
    assert decode_swim(plain_bytes).digest is None

    # emulated pre-r12 decoder on the NEW packet: reads through the
    # updates list and stops — the ext bytes are simply left unread
    r = Reader(dig_bytes)
    assert MsgKind(r.u8()) == MsgKind.PING
    assert r.u32() == 9
    assert read_actor(r) == a
    assert r.u8() == 0 and r.u8() == 0  # no target / origin
    n = r.u16()
    assert n == 1
    assert read_actor(r) == b and r.u32() == 2 and r.u8() == 0
    assert not r.eof()  # trailing digest ext, invisible to old readers
    # ...and on the digest-free packet the old decoder consumes it ALL
    r2 = Reader(plain_bytes)
    r2.u8(), r2.u32(), read_actor(r2), r2.u8(), r2.u8()
    for _ in range(r2.u16()):
        read_actor(r2), r2.u32(), r2.u8()
    assert r2.eof()


def test_encode_once_wire_body_byte_identical():
    """r14 encode-once: a ChangeV1 carrying its pre-serialized body
    (`with_wire_body` at commit, or captured from the frame at decode)
    encodes to EXACTLY the bytes of a fresh full encode — on the uni
    plane (with and without stamps/digest) and on the sync plane."""
    from corrosion_tpu.types.codec import (
        decode_uni_payload_ext,
        encode_change_v1_body,
        with_wire_body,
    )

    cv = ChangeV1(
        actor_id=ActorId(b"\x22" * 16),
        changeset=ChangesetFull(
            version=7,
            changes=(mk_change(), mk_change(cid="num", val=42, seq=1)),
            seqs=(0, 1),
            last_seq=1,
            ts=Timestamp(123456789),
        ),
        origin_ts=1723.5,
        traceparent="00-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
    )
    stamped = with_wire_body(cv)
    assert stamped.wire_body == encode_change_v1_body(cv)
    assert stamped == cv  # wire_body is a cache, never identity

    for digest in (None, b"\x05digestbytes"):
        fresh = encode_uni_payload(cv, ClusterId(3), digest=digest)
        shared = encode_uni_payload(stamped, ClusterId(3), digest=digest)
        assert shared == fresh

    assert encode_sync_msg(stamped) == encode_sync_msg(cv)

    # decode captures the received body so a RELAY also wraps, not
    # re-encodes — and the captured bytes are the true body bytes
    out, _cluster, _dig = decode_uni_payload_ext(
        encode_uni_payload(cv, ClusterId(3))
    )
    assert out.wire_body == encode_change_v1_body(cv)
    assert encode_uni_payload(out, ClusterId(3)) == encode_uni_payload(
        cv, ClusterId(3)
    )


def test_encode_once_prefix_retransmission_digest():
    """Re-transmissions share the prefix: appending a per-transmission
    digest ext to the cached prefix equals a full encode with that
    digest, and the digest-free payload is a strict prefix-equal reuse."""
    from corrosion_tpu.types.codec import (
        encode_uni_from_prefix,
        encode_uni_prefix,
        with_wire_body,
    )

    cv = with_wire_body(ChangeV1(
        actor_id=ActorId(b"\x33" * 16),
        changeset=ChangesetFull(
            version=2,
            changes=(mk_change(),),
            seqs=(0, 0),
            last_seq=0,
            ts=Timestamp(5),
        ),
        origin_ts=99.25,
    ))
    prefix = encode_uni_prefix(cv, ClusterId(1))
    base = encode_uni_from_prefix(prefix, cv.origin_ts, cv.traceparent)
    assert base == encode_uni_payload(cv, ClusterId(1))
    for digest in (b"d1", b"other-digest"):
        assert encode_uni_from_prefix(
            prefix, cv.origin_ts, cv.traceparent, digest
        ) == encode_uni_payload(cv, ClusterId(1), digest=digest)


def test_chunked_change_v1_bodies_byte_identical():
    """r16 broadcast chunking: `chunked_change_v1` splices each chunk's
    body from cached `wire_cell` bytes (header pack + cell join + tail
    pack) — the bytes must be IDENTICAL to a full `encode_change_v1_body`
    walk over the equivalent ChangesetFull, whether or not the input
    changes carry wire_cell caches, and the chunk seq ranges must tile
    0..last_seq exactly like `chunk_changes`."""
    from corrosion_tpu.types.change import chunk_changes
    from corrosion_tpu.types.codec import (
        Writer,
        chunked_change_v1,
        encode_change_v1_body,
        write_change_fields,
    )

    actor = ActorId(b"\x33" * 16)
    ts = Timestamp(987654321)
    changes = tuple(
        mk_change(
            cid=f"c{i % 5}",
            val=("x" * (200 * (i % 7))) if i % 3 else i,
            seq=i,
        )
        for i in range(40)
    )

    def with_cells(chs):
        out = []
        for c in chs:
            w = Writer()
            write_change_fields(
                w, c.table, c.pk, c.cid, c.val, c.col_version,
                c.db_version, c.seq, c.site_id, c.cl,
            )
            out.append(Change(**{**c.__dict__, "wire_cell": w.bytes()}))
        return tuple(out)

    for variant in (changes, with_cells(changes)):
        chunks = chunked_change_v1(
            actor, 7, variant, 39, ts,
            origin_ts=17.5, traceparent=None, max_bytes=2048,
        )
        assert len(chunks) > 1  # the shape actually chunked
        expect = [
            (tuple(chunk), seqs)
            for chunk, seqs in chunk_changes(variant, 39, max_bytes=2048)
        ]
        assert [
            (cv.changeset.changes, cv.changeset.seqs) for cv in chunks
        ] == expect
        # contiguous coverage 0..last_seq
        assert chunks[0].changeset.seqs[0] == 0
        assert chunks[-1].changeset.seqs[1] == 39
        for a, b in zip(chunks, chunks[1:]):
            assert b.changeset.seqs[0] == a.changeset.seqs[1] + 1
        for cv in chunks:
            ref = ChangeV1(actor_id=actor, changeset=cv.changeset)
            assert cv.wire_body == encode_change_v1_body(ref)
            # and the whole uni payload splices to the fresh encode
            assert encode_uni_payload(cv, ClusterId(2)) == (
                encode_uni_payload(
                    ChangeV1(
                        actor_id=actor, changeset=cv.changeset,
                        origin_ts=cv.origin_ts,
                        traceparent=cv.traceparent,
                    ),
                    ClusterId(2),
                )
            )


def test_chunked_change_v1_partial_source_keeps_seq_claim():
    """Re-chunking an already-partial changeset (broadcast oversize
    splitting of a relayed frame) must never claim seq coverage outside
    the source's own range: chunk ranges tile seqs[0]..seqs[1], while
    last_seq stays the full version's."""
    from corrosion_tpu.types.codec import chunked_change_v1

    actor = ActorId(b"\x44" * 16)
    ts = Timestamp(5)
    # a partial carrying seqs 100..139 of a version whose last_seq=500
    changes = tuple(
        mk_change(cid="text", val="y" * 300, seq=100 + i) for i in range(40)
    )
    chunks = chunked_change_v1(
        actor, 9, changes, 500, ts, max_bytes=2048, seq_range=(100, 139),
    )
    assert len(chunks) > 1
    assert chunks[0].changeset.seqs[0] == 100
    assert chunks[-1].changeset.seqs[1] == 139
    for a, b in zip(chunks, chunks[1:]):
        assert b.changeset.seqs[0] == a.changeset.seqs[1] + 1
    for cv in chunks:
        assert cv.changeset.last_seq == 500
        lo, hi = cv.changeset.seqs
        assert {c.seq for c in cv.changeset.changes} == set(
            range(lo, hi + 1)
        )

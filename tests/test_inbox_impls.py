"""Bit-equality of the three gossip-inbox builds (flat sort / grouped
sort / pallas sequential scatter) — `ops/swim.py:build_inbox`,
`build_inbox_grouped`, `ops/inbox_pallas.py:build_inbox_pallas`.

The inbox is the tick's hottest phase; any divergence between impls
would silently fork protocol behavior per flag, so equality is exact
(int32 ==), randomized over destinations/masks, including degenerate
all-masked and everything-collides cases."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import swim
from corrosion_tpu.ops.inbox_pallas import build_inbox_pallas


def _flat_reference(n, slots, dst_g, subj, key, ok):
    """The r3 flat path, verbatim semantics (masked → dst=n sentinel)."""
    dst = jnp.where(ok, dst_g[:, None], n).reshape(-1)
    s = jnp.where(ok, subj, n).reshape(-1)
    k = jnp.where(ok, key, 0).reshape(-1)
    return swim.build_inbox(n, slots, dst, s, k)


def _random_case(seed, n, g, m, p_ok, dst_spread):
    rng = np.random.default_rng(seed)
    dst = rng.integers(0, dst_spread, size=g).astype(np.int32)
    subj = rng.integers(0, n, size=(g, m)).astype(np.int32)
    key = rng.integers(1, 2**20, size=(g, m)).astype(np.int32)
    ok = rng.random((g, m)) < p_ok
    return (
        jnp.asarray(dst),
        jnp.asarray(subj),
        jnp.asarray(key),
        jnp.asarray(ok),
    )


CASES = [
    # (n, g, m, slots, p_ok, dst_spread)
    (64, 128, 10, 16, 0.8, 64),   # typical shape
    (64, 128, 10, 4, 0.8, 8),     # heavy collisions, tight slots
    (16, 400, 3, 2, 0.5, 16),     # overflow everywhere
    (32, 64, 10, 16, 0.0, 32),    # all masked
    (32, 64, 10, 16, 1.0, 1),     # single destination takes all
]


@pytest.mark.parametrize("case", CASES)
def test_gsort_bit_equal(case):
    n, g, m, slots, p_ok, spread = case
    for seed in range(3):
        dst, subj, key, ok = _random_case(seed, n, g, m, p_ok, spread)
        ref_s, ref_k = _flat_reference(n, slots, dst, subj, key, ok)
        got_s, got_k = swim.build_inbox_grouped(
            n, slots, dst, subj, key, ok
        )
        assert jnp.array_equal(ref_s, got_s)
        assert jnp.array_equal(ref_k, got_k)


@pytest.mark.parametrize("case", CASES)
def test_pallas_bit_equal(case):
    n, g, m, slots, p_ok, spread = case
    dst, subj, key, ok = _random_case(99, n, g, m, p_ok, spread)
    ref_s, ref_k = _flat_reference(n, slots, dst, subj, key, ok)
    got_s, got_k = build_inbox_pallas(n, slots, dst, subj, key, ok)
    assert jnp.array_equal(ref_s, got_s)
    assert jnp.array_equal(ref_k, got_k)


@pytest.mark.parametrize("impl", ["gsort", "pallas"])
def test_tick_bit_equal_across_impls(impl):
    """A full SWIM tick produces identical state under every inbox impl."""
    n = 64
    base = swim.SwimParams(
        n=n, feeds_per_tick=2, feed_entries=16, inbox_impl="sort"
    )
    other = base._replace(inbox_impl=impl)
    rng = jax.random.PRNGKey(7)
    state = swim.init_state(base, rng)
    s_ref, s_alt = state, state
    for t in range(5):
        r = jax.random.fold_in(rng, t)
        s_ref = swim.tick_impl(s_ref, r, base)
        s_alt = swim.tick_impl(s_alt, r, other)
    for a, b in zip(s_ref, s_alt):
        assert jnp.array_equal(a, b)


def test_dispatch_unknown_impl_raises():
    n, g, m, slots = 16, 32, 4, 8
    dst, subj, key, ok = _random_case(5, n, g, m, 0.7, n)
    with pytest.raises(ValueError, match="inbox_impl"):
        swim.dispatch_inbox("definitely-not", n, slots, dst, subj, key, ok)

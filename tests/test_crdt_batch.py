"""Randomized equivalence: batched apply_changes vs per-row _apply_one.

`CrdtStore.apply_changes` (round-2 batched ingestion path) must produce a
database state and impactful-set identical to the per-row reference
implementation `_apply_one` (the direct transliteration of cr-sqlite's
merge rules, `klukai-agent/src/agent/util.rs:1206-1310`) for ANY change
sequence — including stale causal lengths, delete/re-create chains within
one batch, equal-(cl, col_version) value races, and unknown tables/columns.
"""

import random

from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import SENTINEL, Change
from corrosion_tpu.types.pack import pack_columns

SCHEMA = (
    "CREATE TABLE kv (id INTEGER NOT NULL PRIMARY KEY,"
    " a TEXT NOT NULL DEFAULT '', b INTEGER NOT NULL DEFAULT 0);"
    "CREATE TABLE other (k TEXT NOT NULL PRIMARY KEY,"
    " v TEXT NOT NULL DEFAULT '');"
)

SITES = [ActorId(bytes([i]) * 16) for i in (1, 2, 3)]


def mk_store() -> CrdtStore:
    st = CrdtStore(":memory:", site_id=ActorId(bytes([9]) * 16))
    st.apply_schema_sql(SCHEMA)
    return st


def random_changes(rng: random.Random, count: int) -> list:
    changes = []
    versions = {s.bytes16: 0 for s in SITES}
    for _ in range(count):
        site = rng.choice(SITES)
        tbl, cid_pool, pk = rng.choices(
            [
                ("kv", ["a", "b"], pack_columns([rng.randint(1, 6)])),
                ("other", ["v"], pack_columns([f"k{rng.randint(1, 4)}"])),
                # unknown table / unknown column: must be dropped by both
                ("nope", ["x"], pack_columns([1])),
                ("kv", ["zz"], pack_columns([1])),
            ],
            weights=[10, 6, 1, 1],
        )[0]
        cl = rng.choice([1, 1, 1, 2, 3, 3, 4, 5])
        if cl % 2 == 0 or rng.random() < 0.1:
            cid, val = SENTINEL, None
        else:
            cid = rng.choice(cid_pool)
            val = (
                rng.randint(0, 5)
                if cid == "b"
                else rng.choice(["x", "y", "zz", ""])
            )
        versions[site.bytes16] += rng.choice([0, 1, 1])
        changes.append(
            Change(
                table=tbl,
                pk=pk,
                cid=cid,
                val=val,
                col_version=rng.randint(1, 4),
                db_version=max(1, versions[site.bytes16]),
                seq=rng.randint(0, 3),
                site_id=site.bytes16,
                cl=cl,
                ts=Timestamp.from_unix(rng.randint(1, 100)),
            )
        )
    return changes


def apply_reference(store: CrdtStore, changes) -> list:
    """The pre-batching per-row application loop (old apply_changes)."""
    impactful = []
    with store._lock:
        store._conn.execute("BEGIN IMMEDIATE")
        # r15: the trigger gate is the in-process capture flag (read by
        # corro_capture_on()), not a __crdt_ctx row
        store._capture_flag[0] = 0
        try:
            for ch in changes:
                if store._apply_one(ch):
                    impactful.append(ch)
                store._bump_db_version(ActorId(ch.site_id), ch.db_version)
            store._conn.execute("COMMIT")
        except BaseException:
            store._conn.execute("ROLLBACK")
            store._dv_cache.clear()
            raise
        finally:
            store._capture_flag[0] = 1
    return impactful


def dump_state(store: CrdtStore) -> dict:
    out = {}
    for tbl in ("kv", "other"):
        out[tbl] = store._conn.execute(
            f'SELECT * FROM "{tbl}" ORDER BY 1'
        ).fetchall()
        out[tbl] = [tuple(r) for r in out[tbl]]
        for suffix in ("__crdt_rows", "__crdt_clock"):
            rows = store._conn.execute(
                f'SELECT * FROM "{tbl}{suffix}" ORDER BY pk'
                + (", cid" if suffix == "__crdt_clock" else "")
            ).fetchall()
            out[tbl + suffix] = [tuple(r) for r in rows]
    out["versions"] = [
        tuple(r)
        for r in store._conn.execute(
            "SELECT site_id, db_version FROM __crdt_db_versions"
            " ORDER BY site_id"
        )
    ]
    return out


def test_batched_matches_reference_randomized():
    for seed in range(12):
        rng = random.Random(seed)
        changes = random_changes(rng, 120)
        a, b = mk_store(), mk_store()
        got = a.apply_changes(changes).impactful
        want = apply_reference(b, changes)
        assert [c for c in got] == [c for c in want], f"seed {seed}"
        assert dump_state(a) == dump_state(b), f"seed {seed}"
        a.close()
        b.close()


def test_batched_split_batches_equal_one_batch():
    """Applying the same sequence as many small batches or one big batch
    must land in the same state (the ingestion queue batches arbitrarily)."""
    rng = random.Random(99)
    changes = random_changes(rng, 150)
    a, b = mk_store(), mk_store()
    a.apply_changes(changes)
    for i in range(0, len(changes), 7):
        b.apply_changes(changes[i : i + 7])
    assert dump_state(a) == dump_state(b)
    a.close()
    b.close()


def test_equal_cv_race_after_recreate_compares_against_default():
    """delete + recreate + equal-(cl,col_version) value write in ONE
    batch: the value comparison must see the recreated row's column
    DEFAULT (what the per-row path reads), not the pre-delete value."""
    site_a, site_b = SITES[0].bytes16, SITES[1].bytes16
    pk = pack_columns([2])
    ts = Timestamp.from_unix(1)

    def seq(store, fn):
        seed = [
            Change(table="kv", pk=pk, cid="b", val=9, col_version=1,
                   db_version=1, seq=0, site_id=site_a, cl=1, ts=ts),
        ]
        store.apply_changes(seed) if fn is None else fn(store, seed)
        batch = [
            Change(table="kv", pk=pk, cid=SENTINEL, val=None, col_version=1,
                   db_version=2, seq=0, site_id=site_a, cl=2, ts=ts),
            Change(table="kv", pk=pk, cid="b", val=0, col_version=1,
                   db_version=3, seq=0, site_id=site_b, cl=3, ts=ts),
            # equal cl + equal col_version as the recreate's write: value
            # race against the recreated cell (b == 0, the default)
            Change(table="kv", pk=pk, cid="b", val=0, col_version=1,
                   db_version=2, seq=1, site_id=site_a, cl=3, ts=ts),
        ]
        return batch

    a, b = mk_store(), mk_store()
    batch = seq(a, None)
    a.apply_changes(batch)
    batch = seq(b, None)
    apply_reference(b, batch)
    assert dump_state(a) == dump_state(b)
    a.close()
    b.close()


def test_native_engine_builds():
    """The columnar native merge engine (native/crdt_batch.cpp) must be
    available in this image — a silent fallback to Python would void the
    native-path coverage of every other test in this module."""
    from corrosion_tpu import native

    assert native.merge_batch_lib() is not None


def rich_value(rng: random.Random):
    """Value generator spanning every sqlite type and the comparison edge
    cases: int64 extremes (exact mixed int/float compare), unicode text
    (memcmp vs code-point order), blobs, empty strings, bools. (No None:
    the test schema's columns are NOT NULL, and a NULL cell write fails
    the flush identically on every path.)"""
    return rng.choice(
        [
            0,
            1,
            -1,
            2**62,
            -(2**62),
            2**53 + 1,
            True,
            False,
            0.0,
            -0.5,
            2.0**53,
            1e300,
            "",
            "x",
            "zz",
            "é中",
            "é",
            b"",
            b"\x00",
            b"\x00\x01",
            b"\xff",
        ]
    )


def random_rich_changes(rng: random.Random, count: int) -> list:
    changes = []
    for i in range(count):
        site = rng.choice(SITES)
        cl = rng.choice([1, 1, 1, 2, 3, 3, 4, 5])
        if cl % 2 == 0 or rng.random() < 0.1:
            cid, val = SENTINEL, None
        else:
            cid = rng.choice(["a", "b"])
            val = rich_value(rng)
        changes.append(
            Change(
                table="kv",
                pk=pack_columns([rng.randint(1, 5)]),
                cid=cid,
                val=val,
                col_version=rng.randint(1, 3),
                db_version=i + 1,
                seq=0,
                site_id=site.bytes16,
                cl=cl,
                ts=Timestamp.from_unix(rng.randint(1, 100)),
            )
        )
    return changes


def test_native_matches_python_randomized(monkeypatch):
    """Native columnar engine vs pure-Python decision loop: identical db
    state and impactful set for value-type-rich random batches (the
    schema's declared types don't constrain cell values — like SQLite,
    any value can land in any column)."""
    from corrosion_tpu.store import crdt as crdt_mod

    for seed in range(10):
        rng = random.Random(1000 + seed)
        changes = random_rich_changes(rng, 150)

        monkeypatch.setenv("CORRO_NATIVE_BATCH", "1")
        a = mk_store()
        got_native = a.apply_changes(changes).impactful
        assert crdt_mod._native_batch_enabled()

        monkeypatch.setenv("CORRO_NATIVE_BATCH", "0")
        b = mk_store()
        got_python = b.apply_changes(changes).impactful
        assert not crdt_mod._native_batch_enabled()

        assert got_native == got_python, f"seed {seed}"
        assert dump_state(a) == dump_state(b), f"seed {seed}"
        a.close()
        b.close()


def test_native_matches_per_row_split_batches(monkeypatch):
    """Native engine across arbitrary batch splits vs the per-row
    reference in one stream."""
    monkeypatch.setenv("CORRO_NATIVE_BATCH", "1")
    rng = random.Random(4242)
    changes = random_rich_changes(rng, 180)
    a, b = mk_store(), mk_store()
    for i in range(0, len(changes), 11):
        a.apply_changes(changes[i : i + 11])
    apply_reference(b, changes)
    assert dump_state(a) == dump_state(b)
    a.close()
    b.close()


def test_delete_then_recreate_in_one_batch_resets_cells():
    """A delete (even cl) followed by a re-create (odd cl) in the SAME
    batch must not leak pre-delete cell values into the recreated row."""
    site = SITES[0].bytes16
    pk = pack_columns([1])
    ts = Timestamp.from_unix(1)
    seed_val = Change(
        table="kv", pk=pk, cid="a", val="old", col_version=1,
        db_version=1, seq=0, site_id=site, cl=1, ts=ts,
    )
    st = mk_store()
    st.apply_changes([seed_val])
    row = st._conn.execute("SELECT a FROM kv WHERE id = 1").fetchone()
    assert row["a"] == "old"

    batch = [
        Change(table="kv", pk=pk, cid=SENTINEL, val=None, col_version=1,
               db_version=2, seq=0, site_id=site, cl=2, ts=ts),
        Change(table="kv", pk=pk, cid=SENTINEL, val=None, col_version=1,
               db_version=3, seq=0, site_id=site, cl=3, ts=ts),
    ]
    # reference store for the same two changes
    ref = mk_store()
    ref.apply_changes([seed_val])
    apply_reference(ref, batch)
    st.apply_changes(batch)
    assert dump_state(st) == dump_state(ref)
    # and the recreated row has default cells, not 'old'
    row = st._conn.execute("SELECT a FROM kv WHERE id = 1").fetchone()
    assert row["a"] == ""
    st.close()
    ref.close()

"""HTTP/2 transport: HPACK, flow control, multiplexing, curl interop,
and the dual-protocol API front-end.

Reference parity: the client is HTTP/2-only (`klukai-client/src/lib.rs:33-47`)
and the hyper server auto-negotiates h2c/h1.1 on the API port. The curl
tests exercise our server against nghttp2 — a real, independent h2 peer.
"""

import asyncio
import json
import shutil

import pytest

from corrosion_tpu.net import hpack
from corrosion_tpu.net.h2 import (
    DEFAULT_WINDOW,
    H2Client,
    H2Server,
    StreamReset,
)

HEADERS = [
    (b":method", b"POST"),
    (b":path", b"/v1/transactions"),
    (b":scheme", b"http"),
    (b":authority", b"127.0.0.1:8080"),
    (b"content-type", b"application/json"),
    (b"authorization", b"Bearer sekrit"),
]


# -- hpack ------------------------------------------------------------------


def test_hpack_nghttp2_roundtrip_with_dynamic_table():
    assert hpack.nghttp2_available()
    d, i = hpack.NgDeflater(), hpack.NgInflater()
    first = d.encode(HEADERS)
    assert i.decode(first) == HEADERS
    second = d.encode(HEADERS)  # dynamic-table hits shrink the block
    assert len(second) < len(first)
    assert i.decode(second) == HEADERS


def test_hpack_python_encode_decodable_by_both():
    enc = hpack.PyDeflater().encode(HEADERS)
    assert hpack.PyInflater().decode(enc) == HEADERS
    assert hpack.NgInflater().decode(enc) == HEADERS  # always-legal encoding


def test_hpack_integer_boundaries():
    # RFC 7541 §5.1: values straddling the prefix limit
    for value in (0, 1, 30, 31, 32, 126, 127, 128, 255, 16383, 2**20):
        enc = hpack._int_encode(value, 5, 0x20)
        got, pos = hpack._int_decode(enc, 0, 5)
        assert got == value and pos == len(enc)


# -- server/client over real sockets ---------------------------------------


@pytest.fixture
def h2_pair():
    loop = asyncio.new_event_loop()

    async def handler(req):
        if req.path.startswith("/echo"):
            body = await req.read_body()
            await req.respond(
                200, b"echo:" + body, {"x-method": req.method}
            )
        elif req.path == "/big":
            # response larger than both flow-control windows
            await req.send_headers(200)
            await req.send_data(b"z" * (DEFAULT_WINDOW * 2 + 123), end_stream=True)
        elif req.path == "/stream":
            await req.send_headers(200)
            for i in range(10):
                await req.send_data(json.dumps({"n": i}).encode() + b"\n")
                await asyncio.sleep(0.01)
            await req.send_data(b"", end_stream=True)
        elif req.path == "/forever":
            await req.send_headers(200)
            while True:
                await req.send_data(b"tick\n")
                await asyncio.sleep(0.01)
        elif req.path == "/forever-noheaders":
            await asyncio.sleep(3600)  # wedged server: no response at all
        else:
            await req.respond(404, b"nope")

    srv = H2Server(handler)
    loop.run_until_complete(srv.start())
    client = H2Client("127.0.0.1", srv.port)
    yield loop, srv, client
    loop.run_until_complete(client.close())
    loop.run_until_complete(srv.stop())
    loop.close()


def test_h2_echo_roundtrip(h2_pair):
    loop, _srv, client = h2_pair

    async def go():
        resp = await client.request("POST", "/echo", body=b"x" * 1000)
        assert resp.status == 200
        assert resp.headers["x-method"] == "POST"
        return await resp.read()

    assert loop.run_until_complete(go()) == b"echo:" + b"x" * 1000


def test_h2_flow_control_large_bodies_both_directions(h2_pair):
    loop, _srv, client = h2_pair
    big = bytes(range(256)) * 1024  # 256 KiB > 64 KiB initial window

    async def go():
        resp = await client.request("POST", "/echo", body=big)
        got = await resp.read()
        assert got == b"echo:" + big
        resp = await client.request("GET", "/big")
        body = await resp.read()
        assert len(body) == DEFAULT_WINDOW * 2 + 123
        assert set(body) == {ord("z")}

    loop.run_until_complete(go())


def test_h2_multiplexed_streams_interleave(h2_pair):
    loop, _srv, client = h2_pair

    async def go():
        async def echo(i):
            r = await client.request("POST", "/echo", body=f"m{i}".encode())
            return (await r.read()).decode()

        async def stream():
            r = await client.request("GET", "/stream")
            return [json.loads(ln) async for ln in _lines(r)]

        a, b, events, c = await asyncio.gather(
            echo(1), echo(2), stream(), echo(3)
        )
        assert (a, b, c) == ("echo:m1", "echo:m2", "echo:m3")
        assert [e["n"] for e in events] == list(range(10))

    async def _lines(resp):
        buf = b""
        async for chunk in resp.body():
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    yield line

    loop.run_until_complete(go())


def test_h2_aclose_rst_stops_infinite_stream(h2_pair):
    loop, srv, client = h2_pair

    async def go():
        resp = await client.request("GET", "/forever")
        it = resp.body()
        assert (await it.__anext__()).startswith(b"tick")
        await resp.aclose()
        # consuming after cancel terminates cleanly instead of hanging
        rest = [c async for c in it]
        assert b"".join(rest) is not None
        # server drops the stream promptly after the RST
        for _ in range(100):
            if not any(s for c in [*srv._conns] for s in c.streams):
                break
            await asyncio.sleep(0.02)

    loop.run_until_complete(asyncio.wait_for(go(), 10))


def test_h2_ping_keepalive(h2_pair):
    loop, _srv, client = h2_pair

    async def go():
        conn = await client._ensure()
        assert await conn.ping(2.0)

    loop.run_until_complete(go())


def test_h2_handler_error_maps_to_500():
    loop = asyncio.new_event_loop()

    async def handler(req):
        raise RuntimeError("boom")

    srv = H2Server(handler)
    loop.run_until_complete(srv.start())
    client = H2Client("127.0.0.1", srv.port)

    async def go():
        resp = await client.request("GET", "/")
        assert resp.status == 500
        await client.close()
        await srv.stop()

    loop.run_until_complete(go())
    loop.close()


# -- curl (nghttp2) interop -------------------------------------------------


@pytest.mark.skipif(shutil.which("curl") is None, reason="no curl")
def test_curl_http2_prior_knowledge_interop():
    loop = asyncio.new_event_loop()

    async def handler(req):
        body = await req.read_body()
        await req.respond(
            200,
            json.dumps(
                {"method": req.method, "path": req.path, "len": len(body)}
            ).encode(),
            {"content-type": "application/json"},
        )

    srv = H2Server(handler)
    loop.run_until_complete(srv.start())

    async def run_curl():
        # async subprocess: the server must keep serving while curl runs
        proc = await asyncio.create_subprocess_exec(
            "curl", "-s", "--http2-prior-knowledge",
            "-X", "POST", "--data-binary", "@-",
            "-w", "\n%{http_version}",
            f"http://127.0.0.1:{srv.port}/v1/transactions",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
        )
        out, _ = await asyncio.wait_for(
            # > one flow-control window: exercises WINDOW_UPDATEs
            proc.communicate(b"q" * 100_000), 30,
        )
        return out

    try:
        out = loop.run_until_complete(run_curl())
        body, version = out.rsplit(b"\n", 1)
        assert version.strip() == b"2"
        parsed = json.loads(body)
        assert parsed == {
            "method": "POST", "path": "/v1/transactions", "len": 100_000
        }
    finally:
        loop.run_until_complete(srv.stop())
        loop.close()


# -- dual-protocol API front-end -------------------------------------------


def test_api_port_serves_h2_and_h1_together():
    """One agent API port: curl over h2c, our client over h2, and an
    HTTP/1.1 aiohttp client — all against the same listener
    (hyper auto-mode parity, `klukai-agent/src/agent/util.rs:181-351`)."""
    from tests.test_http_api import boot_with_api
    from corrosion_tpu.client import CorrosionApiClient
    from corrosion_tpu.net.mem import MemNetwork

    async def main():
        net = MemNetwork(seed=77)
        a, api, client = await boot_with_api(net, "agent-h2")
        addr = api.addrs[0]
        try:
            # h2 client (the default): write + read
            res = await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "h2"]]]
            )
            assert res["results"][0]["rows_affected"] == 1
            assert isinstance(client._session.h2, H2Client)  # really h2

            # h1 client on the same port
            h1 = CorrosionApiClient(addr, http2=False)
            rows = await h1.query_rows(["SELECT text FROM tests", []])
            assert rows == [["h2"]]
            await h1.close()

            # curl h2c prior knowledge on the same port
            proc = await asyncio.create_subprocess_exec(
                "curl", "-s", "--http2-prior-knowledge",
                "-X", "POST", "-H", "content-type: application/json",
                "-d", json.dumps(["SELECT id, text FROM tests"]),
                "-w", "\n%{http_version}",
                f"http://{addr}/v1/queries",
                stdout=asyncio.subprocess.PIPE,
            )
            out, _ = await asyncio.wait_for(proc.communicate(), 30)
            body, version = out.rsplit(b"\n", 1)
            assert version.strip() == b"2"
            lines = [json.loads(x) for x in body.splitlines() if x.strip()]
            assert lines[0] == {"columns": ["id", "text"]}
            assert {"row": [1, [1, "h2"]]} in lines
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_h2_continuation_split_preserves_end_stream(h2_pair):
    """A >MAX_FRAME_SIZE header block must ride CONTINUATION frames
    (RFC 9113 §4.2), and END_STREAM from the initial HEADERS must
    survive reassembly — a bodyless request with huge headers would
    otherwise hang the handler's read_body() forever."""
    loop, _srv, client = h2_pair

    async def go():
        # ~3 x 16384 of incompressible header data on a bodyless GET
        big = {f"x-pad-{i}": "v" * 800 for i in range(60)}
        resp = await asyncio.wait_for(
            client.request("GET", "/echo", headers=big), 10
        )
        assert resp.status == 200
        assert (await resp.read()) == b"echo:"  # END_STREAM was seen

    loop.run_until_complete(go())


def test_h2_request_timeout_cancel_does_not_leak_stream(h2_pair):
    loop, _srv, client = h2_pair

    async def go():
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(
                client.request("GET", "/forever-noheaders"), 0.3
            )
        conn = await client._ensure()
        # cancelled request must deregister its stream (no orphan queue)
        for _ in range(50):
            if not conn.streams:
                break
            await asyncio.sleep(0.02)
        assert conn.streams == {}
        # connection still serves new requests afterwards
        r = await client.request("POST", "/echo", body=b"after")
        assert (await r.read()) == b"echo:after"

    loop.run_until_complete(go())


def test_h2_server_robust_to_malformed_input():
    """Hostile/garbage input: bad preface, truncated frames, unknown
    frame types, HEADERS with undecodable HPACK, frames on stream 0 —
    the server must close or ignore, never hang or crash, and keep
    serving healthy connections."""
    loop = asyncio.new_event_loop()

    async def handler(req):
        await req.respond(200, b"ok")

    srv = H2Server(handler)
    loop.run_until_complete(srv.start())

    import random
    import struct as _struct
    from corrosion_tpu.net.h2 import PREFACE

    rnd = random.Random(1234)

    def frame(ftype, flags, sid, payload):
        return (
            _struct.pack(">I", len(payload))[1:]
            + bytes([ftype, flags])
            + _struct.pack(">I", sid)
            + payload
        )

    async def attempt(raw: bytes, expect_close: bool = False):
        """expect_close: the server MUST reach EOF (GOAWAY + close) within
        the bound — one read() is NOT enough, the initial SETTINGS frame
        would satisfy it and mask a post-SETTINGS silent hang."""
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", srv.port)
            writer.write(raw)
            await writer.drain()
            try:
                while True:  # drain to EOF
                    # 2 s bound (was 5): every lenient/garbage case that
                    # legitimately waits for more input pays this in
                    # full, and there are ~6 of them — the old value
                    # alone cost this test ~15 s of tier-1 wall (r16
                    # budget audit); in-process loopback GOAWAYs arrive
                    # in milliseconds, so the margin stays ~100×
                    data = await asyncio.wait_for(reader.read(65536), 2)
                    if not data:
                        break
            except asyncio.TimeoutError:
                assert not expect_close, (
                    f"server sat silent (no close) on {raw[:40]!r}…"
                )
            writer.close()
        except (ConnectionError, OSError):
            pass

    async def go():
        # deterministic protocol violations after a full preface: the
        # server must answer (GOAWAY / settings then close) — never hang
        strict_cases = [
            PREFACE + frame(0x1, 0x4, 3, b"\xff\xff\xff"),  # bad hpack block
            PREFACE + frame(0x4, 0x0, 0, b"12345"),         # bad SETTINGS len
            PREFACE + frame(0x8, 0x0, 0, b"\x00\x00"),      # bad WINDOW_UPDATE
            PREFACE + frame(0x3, 0x0, 1, b"\x00"),          # bad RST len
            PREFACE + b"\xff" * 200,                        # oversized frame hdr
            # RFC 9113 §5.1.1/§6.1 connection errors (r4 advisor): the
            # server must GOAWAY(PROTOCOL_ERROR), not silently consume
            PREFACE + frame(0x0, 0x0, 0, b"data-on-zero"),  # DATA on stream 0
            PREFACE + frame(0x0, 0x0, 2, b"data-even"),     # DATA on even sid
            PREFACE + frame(0x0, 0x0, 1, b"data-idle"),     # DATA, no HEADERS
            PREFACE + frame(0x1, 0x4, 0, b""),              # HEADERS on 0
            PREFACE + frame(0x1, 0x4, 2, b""),              # HEADERS on even
        ]
        # these legitimately wait for more input; bounded-close is enough
        lenient_cases = [
            b"GET / HTTP/1.0\r\n\r\n",                      # not h2 at all
            PREFACE[:10],                                   # truncated preface
            PREFACE + frame(0xEE, 0x0, 1, b"unknown"),      # unknown type
        ]
        for raw in strict_cases:
            await asyncio.wait_for(attempt(raw, expect_close=True), 15)
        for raw in lenient_cases:
            await asyncio.wait_for(attempt(raw), 15)
        for _ in range(3):
            await attempt(PREFACE + bytes(rnd.randbytes(rnd.randint(9, 400))))
        # a healthy client still gets served afterwards
        client = H2Client("127.0.0.1", srv.port)
        resp = await asyncio.wait_for(client.request("GET", "/"), 10)
        assert resp.status == 200 and (await resp.read()) == b"ok"
        await client.close()

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 60))
    finally:
        loop.run_until_complete(srv.stop())
        loop.close()

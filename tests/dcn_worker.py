"""Worker process for the 2-process DCN mesh test (test_dcn_multiprocess).

Each worker owns 4 virtual CPU devices; jax.distributed stitches the two
processes into one 8-device job over localhost gRPC — the CI-scale stand-in
for the reference's multi-process QUIC mesh (one process per node,
SURVEY §2.6 comm-backend row). The [hosts, members] mesh then spans both
processes; per-tick cross-shard collectives actually cross the process
boundary, which is exactly what the degenerate single-process test could
never exercise.

Prints one JSON line: replicated membership stats + a state fingerprint.
Bit-parity with the single-process flat-mesh run is asserted by the parent.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

# argv[5] (optional) = local virtual devices per process; the 2-proc test
# uses 4, the 4-proc variant 2 — same 8-device job, wider host axis
N_LOCAL = int(sys.argv[5]) if len(sys.argv) > 5 else 4

jaxenv.force_cpu_inprocess(n_devices=N_LOCAL)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    coord = sys.argv[1]
    pid = int(sys.argv[2])
    nprocs = int(sys.argv[3])
    n_ticks = int(sys.argv[4])
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=nprocs, process_id=pid
    )
    assert len(jax.devices()) == N_LOCAL * nprocs, jax.devices()

    from corrosion_tpu.ops import swim
    from corrosion_tpu.parallel import (
        multihost_member_mesh,
        shard_member_state,
        sharded_tick,
    )

    mesh = multihost_member_mesh()
    assert mesh.devices.shape == (nprocs, N_LOCAL), mesh.devices.shape

    params = swim.SwimParams(n=8 * N_LOCAL * nprocs)
    state = shard_member_state(
        swim.init_state(params, jax.random.PRNGKey(3)), mesh
    )
    tick = sharded_tick(params, mesh)
    rng = jax.random.PRNGKey(9)
    for _ in range(n_ticks):
        rng, key = jax.random.split(rng)
        state = tick(state, key)

    # replicated reductions: every process computes the same full-cluster
    # values, so both workers must print identical lines
    stats = {k: float(v) for k, v in swim.membership_stats(state).items()}
    fp = int(jnp.sum((state.view.astype(jnp.int32) * 92821) % 1000003))
    print(
        json.dumps(
            {"pid": pid, "fingerprint": fp, "stats": stats}, sort_keys=True
        ),
        flush=True,
    )
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()

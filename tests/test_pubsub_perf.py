"""Deterministic O(batch) pins for the subscription serving plane (r10).

The perf round's contract, pinned WITHOUT wall clocks:

1. `handle_candidates` executes the SAME per-batch SQL statement
   sequence regardless of table size — the sqlite trace callback counts
   statements at two table sizes for an identical candidate batch.
   (The pre-r10 engine re-created `state_results` per batch and its
   diff plans flipped to full scans of the materialized table as it
   grew; the statement STREAM was size-independent but the work was
   not — the statement pin guards the structure, PUBSUB_BENCH.json
   guards the constant.)
2. No DDL inside the steady-state batch loop: the temp pk tables and
   `state_results` persist across batches (DELETE + INSERT, never
   DROP/CREATE), so prepared statements survive.
3. The manager's inverted routing index feeds ONLY matchers whose
   (table, cid) — or table sentinel — hits, with candidate sets
   identical to what `filter_candidates` would have computed, and
   `filter_candidates` itself stays off the routed hot path.
"""

import asyncio

import pytest

from corrosion_tpu.pubsub.manager import SubsManager
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import SENTINEL, Change
from corrosion_tpu.types.pack import pack_columns

SCHEMA = """
CREATE TABLE items (
  id INTEGER NOT NULL PRIMARY KEY,
  name TEXT NOT NULL DEFAULT '',
  qty INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE other (
  oid INTEGER NOT NULL PRIMARY KEY,
  label TEXT
);
"""


def make_store(n_rows: int = 0):
    store = CrdtStore(":memory:")
    store.apply_schema_sql(SCHEMA)
    if n_rows:
        with store.write_tx(Timestamp(0)) as tx:
            for i in range(n_rows):
                tx.execute(
                    "INSERT INTO items (id, name, qty) VALUES (?, ?, ?)",
                    (i, f"n{i}", i),
                )
            tx.commit()
    return store


def write(store, sql, params=()):
    with store.write_tx(Timestamp(0)) as tx:
        tx.execute(sql, params)
        changes, _v, _s = tx.commit()
    return changes


def run_async(coro):
    return asyncio.run(coro)


def _candidates(pks):
    return {"items": {pack_columns((i,)) for i in pks}}


async def _traced_batches(n_rows, batches):
    """Subscribe over a table of `n_rows`, run the given candidate
    batches through handle_candidates, and return the traced statement
    list per batch."""
    store = make_store(n_rows)
    subs = SubsManager(store)
    handle, _ = await subs.get_or_insert(
        "SELECT id, name FROM items WHERE qty >= 0"
    )
    traces = []
    for pks in batches:
        # mutate the driving table so the diff has real work to do
        for i in pks:
            write(
                store,
                "UPDATE items SET name = name || 'x' WHERE id = ?",
                (i,),
            )
        stmts = []
        handle.matcher._conn.set_trace_callback(stmts.append)
        handle.matcher.handle_candidates(_candidates(pks))
        handle.matcher._conn.set_trace_callback(None)
        traces.append(stmts)
    await subs.stop_all()
    return traces


def test_statement_count_independent_of_table_size():
    """The O(batch) pin: an identical candidate batch executes the
    identical statement sequence at 100 rows and at 2000 rows."""

    async def main():
        batch = [list(range(10)), list(range(10, 30))]
        small = await _traced_batches(100, batch)
        large = await _traced_batches(2000, batch)
        for b_small, b_large in zip(small, large):
            assert len(b_small) == len(b_large), (
                f"per-batch statement count depends on table size:"
                f" {len(b_small)} vs {len(b_large)}"
            )
            # not just the count: the statement TEXTS match 1:1 (same
            # prepared plans reused at either size)
            assert b_small == b_large

    run_async(main())


def test_no_ddl_in_steady_state_batches():
    """Persistent temp/state tables: after the first batch, subsequent
    batches issue zero CREATE/DROP/ALTER and reuse byte-identical
    statement text (prepared-statement cache stays warm)."""

    async def main():
        traces = await _traced_batches(
            200, [list(range(5)), list(range(5)), list(range(5))]
        )
        for stmts in traces:
            for s in stmts:
                head = s.lstrip().upper()
                assert not head.startswith(("CREATE", "DROP", "ALTER")), (
                    f"DDL inside the batch loop: {s}"
                )
        # identical batches → identical statement streams (2nd vs 3rd);
        # the trace interpolates bound values, so compare statement
        # SHAPES (text up to the first string literal)
        def shape(stmts):
            import re

            # bound values + monotonic change ids vary; structure must not
            return [re.sub(r"\d+", "N", s.split("'")[0]) for s in stmts]

        assert shape(traces[1]) == shape(traces[2])

    run_async(main())


# -- routing index --------------------------------------------------------


def _chg(table, pk, cid, val="v", cl=1):
    return Change(
        table=table,
        pk=pack_columns((pk,)),
        cid=cid,
        val=val,
        col_version=1,
        db_version=1,
        seq=0,
        site_id=b"\x01" * 16,
        cl=cl,
    )


class _Spy:
    """Record enqueue_candidates / filter_candidates per handle."""

    def __init__(self, handle):
        self.enqueued = []
        self.filtered = 0
        self._orig_filter = handle.matcher.filter_candidates
        handle.enqueue_candidates = self.enqueue  # type: ignore
        handle.matcher.filter_candidates = self.filter  # type: ignore

    def enqueue(self, cands, stamp=None):
        self.enqueued.append(cands)

    def filter(self, changes):
        self.filtered += 1
        return self._orig_filter(changes)


def test_router_cid_filter_and_sentinel_fanout():
    async def main():
        store = make_store()
        subs = SubsManager(store)
        h_name, _ = await subs.get_or_insert("SELECT name FROM items")
        h_qty, _ = await subs.get_or_insert("SELECT qty FROM items")
        h_other, _ = await subs.get_or_insert("SELECT label FROM other")
        spies = {h.id: _Spy(h) for h in (h_name, h_qty, h_other)}

        # a change on items.name routes to the name matcher only
        subs.match_changes([_chg("items", 1, "name")])
        assert len(spies[h_name.id].enqueued) == 1
        assert spies[h_qty.id].enqueued == []
        assert spies[h_other.id].enqueued == []

        # pk (id) is in every items matcher's deps
        subs.match_changes([_chg("items", 2, "id")])
        assert len(spies[h_name.id].enqueued) == 2
        assert len(spies[h_qty.id].enqueued) == 1

        # sentinel (row create/delete) fans out to every items matcher
        subs.match_changes([_chg("items", 3, SENTINEL, cl=2)])
        assert len(spies[h_name.id].enqueued) == 3
        assert len(spies[h_qty.id].enqueued) == 2
        assert spies[h_other.id].enqueued == []

        # a column no items matcher projects... every column of items is
        # a dep of one of the two matchers, so use other.label vs the
        # items matchers: the other-table matcher hits, items' do not
        subs.match_changes([_chg("other", 4, "label")])
        assert len(spies[h_other.id].enqueued) == 1

        # the routed hot path NEVER calls filter_candidates — matchers
        # with no index hit did no per-change work at all
        assert all(s.filtered == 0 for s in spies.values())
        await subs.stop_all()

    run_async(main())


def test_router_candidates_match_filter_semantics():
    """Routing ≡ filtering: for a mixed change batch, every handle's
    routed candidate sets equal what its own filter_candidates would
    have produced (the pre-r10 semantics, amortized)."""

    async def main():
        store = make_store()
        subs = SubsManager(store)
        handles = [
            (await subs.get_or_insert("SELECT name FROM items"))[0],
            (await subs.get_or_insert("SELECT qty FROM items"))[0],
            (await subs.get_or_insert("SELECT label FROM other"))[0],
        ]
        changes = [
            _chg("items", 1, "name"),
            _chg("items", 1, "qty"),
            _chg("items", 2, SENTINEL, cl=2),
            _chg("other", 7, "label"),
            _chg("other", 8, SENTINEL),
            _chg("ghost_table", 9, "x"),  # unknown table: routed nowhere
        ]
        spies = {h.id: _Spy(h) for h in handles}
        subs.match_changes(changes)
        for h in handles:
            merged = {}
            for cands in spies[h.id].enqueued:
                for t, pks in cands.items():
                    merged.setdefault(t, set()).update(pks)
            expected = spies[h.id]._orig_filter(changes)
            assert merged == expected, (h.sql, merged, expected)
        await subs.stop_all()

    run_async(main())


def test_router_updates_on_subscribe_and_remove():
    async def main():
        store = make_store()
        subs = SubsManager(store)
        assert subs._router == {}
        h, _ = await subs.get_or_insert("SELECT name FROM items")
        assert "items" in subs._router
        assert SENTINEL in subs._router["items"]
        await subs.remove(h.id)
        assert subs._router == {}
        # a change after removal routes nowhere and does not blow up
        subs.match_changes([_chg("items", 1, "name")])
        await subs.stop_all()

    run_async(main())


def test_dead_handle_changes_since_raises():
    from corrosion_tpu.pubsub.matcher import MatcherError

    async def main():
        store = make_store()
        subs = SubsManager(store)
        h, _ = await subs.get_or_insert("SELECT name FROM items")
        h.error = "diff exploded"
        with pytest.raises(MatcherError):
            h.changes_since(0)
        h.error = None
        await subs.stop_all()

    run_async(main())


def test_candidate_batch_wait_config_shrinks_match_latency():
    """[pubsub] candidate_batch_wait (r12): the matcher's
    candidate-batching window is the floor under the observed
    `corro.e2e.match` stage — the r11 SLO plane attributed the ~600 ms
    write→event p50 to exactly the hard-coded 0.6 s default.  Now that
    the window is an operator knob, pin both halves: a high value shows
    up as a structural latency floor, and lowering it shrinks the
    observed match-stage histogram."""
    import time as _time

    from corrosion_tpu.runtime import latency as lat
    from corrosion_tpu.runtime.latency import BatchStamp

    async def run_once(wait, batches=3):
        store = make_store(50)
        subs = SubsManager(store, batch_wait=wait)
        handle, _ = await subs.get_or_insert(
            "SELECT id, name FROM items WHERE qty >= 0"
        )
        assert handle.batch_wait == wait  # knob reaches the cmd loop
        q = handle.attach()
        before = lat.stage_hists(window_secs=None)["match"]
        for i in range(batches):
            write(
                store,
                "UPDATE items SET name = name || 'y' WHERE id = ?",
                (i,),
            )
            handle.enqueue_candidates(
                _candidates([i]),
                BatchStamp(origin=None, applied=_time.time()),
            )
            await asyncio.wait_for(q.get(), 30)
        after = lat.stage_hists(window_secs=None)["match"]
        handle.detach(q)
        await subs.stop_all()
        d = after.diff(before)
        assert d.count == batches
        return d

    async def main():
        lo = await run_once(0.05)
        hi = await run_once(0.5)
        # structural: nothing beats the batching window — every sample
        # waited out the full deadline before the diff ran
        assert hi.quantile(0.5) >= 0.45, hi.nonzero_buckets()
        # directional: the lowered knob shrinks the observed stage
        assert lo.quantile(0.5) < hi.quantile(0.5), (
            lo.nonzero_buckets(), hi.nonzero_buckets(),
        )
        assert lo.total / lo.count < hi.total / hi.count

    run_async(main())

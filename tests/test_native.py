"""Native C++ CRDT extension: build, load, and byte-parity with the
Python pack/compare implementations (the cr-sqlite-equivalent native
layer; reference loads its prebuilt extension in sqlite.rs:125-143)."""

import sqlite3

import pytest

from corrosion_tpu import native
from corrosion_tpu.types.pack import pack_columns, unpack_columns
from corrosion_tpu.types.values import cmp_values

pytestmark = pytest.mark.skipif(
    native.extension_path() is None,
    reason="native toolchain/headers unavailable",
)


@pytest.fixture
def conn():
    c = sqlite3.connect(":memory:")
    assert native.load_into(c)
    yield c
    c.close()


CASES = [
    (),
    (None,),
    (0,),
    (1,),
    (255,),            # the sign-extension quirk row
    (-1,),
    (127, 128, 129),
    (2**31, 2**40, 2**62),
    (-(2**62),),
    (1.5,),
    (0.0,),
    (-273.15,),
    ("",),
    ("hello",),
    ("héllo wörld",),
    ("x" * 300,),      # text length needing 2 bytes
    (b"",),
    (b"\x00\x01\x02",),
    (b"\xff" * 256,),
    (1, "two", 3.0, b"four", None),
]


def native_pack(conn, values):
    n = len(values)
    if n == 0:
        return conn.execute("SELECT crdt_pack()").fetchone()[0]
    q = ", ".join("?" * n)
    return conn.execute(f"SELECT crdt_pack({q})", values).fetchone()[0]


def test_pack_parity(conn):
    for values in CASES:
        got = native_pack(conn, tuple(values))
        want = pack_columns(tuple(values))
        assert got == want, f"mismatch for {values!r}: {got!r} != {want!r}"


def test_pack_roundtrips_through_python_unpack(conn):
    for values in CASES:
        got = native_pack(conn, tuple(values))
        out = unpack_columns(got)
        assert len(out) == len(values)


def test_unpack_n(conn):
    blob = native_pack(conn, (1, "a", None))
    assert conn.execute(
        "SELECT crdt_unpack_n(?)", (blob,)
    ).fetchone()[0] == 3


CMP_CASES = [
    (None, None),
    (None, 1),
    (1, 2),
    (2, 1),
    (1, 1),
    (1, 1.5),
    (2.5, 2),
    (1, "a"),
    ("a", "b"),
    ("b", "a"),
    ("a", "ab"),
    ("a", b"a"),
    (b"\x01", b"\x02"),
    (b"ab", b"ab"),
    (b"a", b"ab"),
    ("", "x"),
    (0, ""),
]


def test_cmp_parity(conn):
    for a, b in CMP_CASES:
        got = conn.execute("SELECT crdt_cmp(?, ?)", (a, b)).fetchone()[0]
        want = cmp_values(a, b)
        assert got == want, f"crdt_cmp({a!r}, {b!r}) = {got} want {want}"
        # antisymmetry
        rev = conn.execute("SELECT crdt_cmp(?, ?)", (b, a)).fetchone()[0]
        assert rev == -want


def test_store_uses_native_pack():
    """End-to-end: a store write produces changes whose pks match the
    Python encoder (triggers call the native crdt_pack)."""
    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.base import Timestamp

    store = CrdtStore(":memory:")
    store.apply_schema_sql(
        "CREATE TABLE t (a INTEGER NOT NULL, b TEXT NOT NULL,"
        " c REAL NOT NULL DEFAULT 0, PRIMARY KEY (a, b));"
    )
    with store.write_tx(Timestamp(1)) as tx:
        tx.execute("INSERT INTO t (a, b, c) VALUES (255, 'k', 1.5)")
        changes, _v, _s = tx.commit()
    assert changes
    # the native trigger packer widens sign-boundary positives exactly
    # like the python packer (see pack.py _num_bytes_needed): 255
    # round-trips instead of upstream's sign-extended -1
    assert unpack_columns(changes[0].pk) == [255, "k"]
    assert all(ch.pk == changes[0].pk for ch in changes)
    store.close()

"""Transport seam: in-memory network, TCP/UDP sockets, gossip codec."""

import asyncio

import pytest

from corrosion_tpu.net.gossip_codec import (
    MAX_PACKET,
    MemberState,
    MemberUpdate,
    MsgKind,
    SwimMessage,
    decode_swim,
    encode_swim,
)
from corrosion_tpu.net.mem import LinkFaults, MemNetwork
from corrosion_tpu.net.tcp import TcpListener, TcpTransport
from corrosion_tpu.net.transport import TransportError
from corrosion_tpu.types.actor import Actor, ActorId
from corrosion_tpu.types.base import Timestamp


def mk_actor(n: int, addr: str) -> Actor:
    return Actor(
        id=ActorId(bytes([n]) * 16), addr=addr, ts=Timestamp.from_unix(1000 + n)
    )


def test_swim_codec_roundtrip():
    a = mk_actor(1, "a:1")
    b = mk_actor(2, "b:2")
    c = mk_actor(3, "c:3")
    msg = SwimMessage(
        kind=MsgKind.PING_REQ,
        probe_no=42,
        sender=a,
        target=b,
        origin=c,
        updates=[
            MemberUpdate(b, 7, MemberState.SUSPECT),
            MemberUpdate(c, 0, MemberState.ALIVE),
        ],
    )
    out = decode_swim(encode_swim(msg))
    assert out.kind == MsgKind.PING_REQ
    assert out.probe_no == 42
    assert out.sender == a
    assert out.target == b
    assert out.origin == c
    assert out.updates == msg.updates
    assert len(encode_swim(msg)) < MAX_PACKET


def test_mem_network_three_lanes():
    async def main():
        net = MemNetwork()
        got = {"dgram": [], "uni": []}

        async def on_datagram(src, data):
            got["dgram"].append((src, data))

        async def on_uni(src, data):
            got["uni"].append((src, data))

        async def on_bi(stream):
            while True:
                frame = await stream.recv()
                if frame is None:
                    break
                await stream.send(b"echo:" + frame)
            await stream.finish()

        net.listener("b").serve(on_datagram, on_uni, on_bi)
        t = net.transport("a")

        await t.send_datagram("b", b"ping")
        await t.send_uni("b", b"bcast")
        bi = await t.open_bi("b")
        await bi.send(b"hello")
        await bi.finish()
        reply = await bi.recv()
        assert reply == b"echo:hello"
        assert await bi.recv() is None
        await asyncio.sleep(0.01)
        assert got["dgram"] == [("a", b"ping")]
        assert got["uni"] == [("a", b"bcast")]

    asyncio.run(main())


def test_mem_network_faults():
    async def main():
        net = MemNetwork(seed=1, faults=LinkFaults(datagram_loss=1.0))
        seen = []

        async def on_datagram(src, data):
            seen.append(data)

        async def noop_uni(src, data):
            pass

        async def noop_bi(stream):
            stream.close()

        net.listener("b").serve(on_datagram, noop_uni, noop_bi)
        t = net.transport("a")
        await t.send_datagram("b", b"x")  # 100% loss: silently dropped
        assert seen == []

        net.faults.datagram_loss = 0.0
        net.partition("a", "b")
        await t.send_datagram("b", b"x")  # partitioned: dropped
        with pytest.raises(TransportError):
            await t.send_uni("b", b"x")  # streams fail loudly
        net.heal("a", "b")
        await t.send_datagram("b", b"y")
        await asyncio.sleep(0.01)
        assert seen == [b"y"]

        net.take_down("b")
        with pytest.raises(TransportError):
            await t.open_bi("b")
        net.bring_up("b")
        bi = await t.open_bi("b")
        assert bi is not None

    asyncio.run(main())


def test_tcp_transport_three_lanes():
    async def main():
        got = {"dgram": asyncio.Event(), "uni": asyncio.Event(), "data": {}}

        async def on_datagram(src, data):
            got["data"]["dgram"] = data
            got["dgram"].set()

        async def on_uni(src, data):
            got["data"].setdefault("uni", []).append(data)
            got["uni"].set()

        async def on_bi(stream):
            frame = await stream.recv()
            await stream.send(b"pong:" + frame)
            await stream.finish()

        server = await TcpListener.bind()
        server.serve(on_datagram, on_uni, on_bi)

        client_listener = await TcpListener.bind()
        client_listener.serve(on_datagram, on_uni, on_bi)
        t = TcpTransport(client_listener)

        await t.send_datagram(server.addr, b"dg")
        await asyncio.wait_for(got["dgram"].wait(), 5)
        assert got["data"]["dgram"] == b"dg"

        # uni lane: two frames over the same cached connection
        await t.send_uni(server.addr, b"frame1")
        await t.send_uni(server.addr, b"frame2")
        await asyncio.wait_for(got["uni"].wait(), 5)
        for _ in range(50):
            if len(got["data"].get("uni", [])) == 2:
                break
            await asyncio.sleep(0.01)
        assert got["data"]["uni"] == [b"frame1", b"frame2"]

        bi = await t.open_bi(server.addr)
        await bi.send(b"syn")
        reply = await bi.recv()
        assert reply == b"pong:syn"
        bi.close()

        await t.close()
        await server.close()
        await client_listener.close()

    asyncio.run(main())


def test_tcp_transport_idle_reaper():
    """gossip.idle_timeout_secs: cached lane conns unused past the
    timeout are reaped on the next cached send (peer/mod.rs:125-127
    max_idle_timeout analog)."""

    async def main():
        async def on_uni(src, data):
            pass

        server = await TcpListener.bind()
        server.serve(lambda s, d: None, on_uni, lambda st: None)
        t = TcpTransport(await TcpListener.bind(), idle_timeout=0.2)

        await t.send_uni(server.addr, b"one")
        assert len(t._conns) == 1
        # not yet idle: opportunistic reap keeps it
        assert t.reap_idle() == 0
        await asyncio.sleep(0.35)
        assert t.reap_idle() == 1
        assert t._conns == {}
        # next send transparently reconnects
        await t.send_uni(server.addr, b"two")
        assert len(t._conns) == 1
        await t.close()
        await server.close()

    asyncio.run(main())


def test_split_addr_ipv6_brackets():
    from corrosion_tpu.net.tcp import split_addr

    assert split_addr("[::1]:8080") == ("::1", 8080)
    assert split_addr("[fe80::1%eth0]:9") == ("fe80::1%eth0", 9)
    assert split_addr("127.0.0.1:8080") == ("127.0.0.1", 8080)


def test_client_parses_bracketed_ipv6_addr():
    from corrosion_tpu.client import CorrosionApiClient

    c = CorrosionApiClient("[::1]:8080")
    assert (c._host, c._port) == ("::1", 8080)
    c4 = CorrosionApiClient("10.0.0.1:8080")
    assert (c4._host, c4._port) == ("10.0.0.1", 8080)


def test_send_cached_lock_revalidation_after_reap():
    """reap_idle can pop a Lock in the release->waiter-resume window; a
    waiter that acquired the orphaned Lock must queue on the current one
    instead of interleaving writes (r4 advisor, tcp.py reaper race)."""
    import asyncio

    from corrosion_tpu.net.tcp import TcpListener, TcpTransport

    async def main():
        got = []

        async def on_uni(src, data):
            got.append(data)

        server = await TcpListener.bind()
        server.serve(lambda s, d: None, on_uni, lambda st: None)
        t = TcpTransport(await TcpListener.bind(), idle_timeout=30.0)
        key = (server.addr, b"U")
        await t.send_uni(server.addr, b"seed")  # create lock + conn

        old_lock = t._locks[key]
        await old_lock.acquire()
        waiter = asyncio.ensure_future(t.send_uni(server.addr, b"queued"))
        await asyncio.sleep(0.05)  # waiter now queued on old_lock
        # simulate the reap window: lock released, waiter not yet resumed,
        # reaper swaps the map entry
        del t._locks[key]
        old_lock.release()
        await asyncio.wait_for(waiter, 5)
        # the waiter must have re-queued onto the CURRENT lock and sent
        assert t._locks[key] is not old_lock
        await asyncio.sleep(0.1)
        assert b"queued" in got
        await t.close()
        await server.close()

    asyncio.run(main())

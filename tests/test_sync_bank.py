"""Banked-record guard for SYNC_SCALE.json (r17 catch-up round).

`scripts/bench_sync.py` banks the cold-node catch-up ladder — a cold
node joining against a 100k/1M-row origin under {quiet, concurrent-
write-fire}, snapshot bootstrap vs pure delta A/B — plus the chaos
loop: partition → heal → catch-up → converge with the cluster
observatory's divergence detector as the oracle.  This guard pins the
artifact's shape and the round's acceptance bars (ISSUE 12).

Margin discipline (r15 memory): this 1-core host drifts ±30% between
runs, so the bars are deterministic facts — full convergence, the
snapshot path actually taken, zero divergence — plus ONE ratio with a
wide margin: snapshot must beat pure delta on the large rung (measured
~7-8x; the bar is >1, an order of magnitude of headroom)."""

from __future__ import annotations

import json
import os

import pytest

PATH = os.path.join(os.path.dirname(__file__), "..", "SYNC_SCALE.json")

RUNGS_100K = [
    "sync-100k-quiet-delta",
    "sync-100k-quiet-snapshot",
    "sync-100k-fire-delta",
    "sync-100k-fire-snapshot",
]
RUNGS_1M = [
    "sync-1000k-quiet-delta",
    "sync-1000k-quiet-snapshot",
    "sync-1000k-fire-snapshot",
]


@pytest.fixture(scope="module")
def banked() -> dict:
    with open(PATH) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def rungs(banked) -> dict:
    return {r["rung"]: r for r in banked["rungs"]}


def test_ladder_shape(rungs):
    for rung in RUNGS_100K + RUNGS_1M:
        assert rung in rungs, f"missing rung {rung}"


def test_records_are_sha_stamped(banked):
    sha = banked.get("code_sha")
    assert sha and "corrosion_tpu/store/snapshot.py" in sha
    assert "corrosion_tpu/agent/catchup.py" in sha
    assert all(v != "missing" for v in sha.values()), sha
    assert banked.get("measured_at")


def test_every_rung_fully_converged(rungs):
    """The bar is FULL convergence, fire included: rows equal, bookie
    gap-free, clock rows equal (the bench asserts those before banking
    `converged`) — and the row counts in-band must be self-consistent
    (2 clock rows per row: one cell + one create sentinel)."""
    for name, rec in rungs.items():
        assert rec["converged"] is True, name
        assert rec["rows_final"] >= rec["rows"], name
        assert rec["clock_rows_final"] == 2 * rec["rows_final"], name
        if rec["fire"]:
            assert rec["fire_rows_written"] > 0, name
            assert rec["rows_final"] == (
                rec["rows"] + rec["fire_rows_written"]
            ), name


def test_snapshot_rungs_took_the_snapshot_path(rungs):
    """A/B integrity: snapshot-mode rungs really installed one
    snapshot; delta-mode rungs never did; and the quiet-snapshot rungs
    moved (almost) nothing over the change stream — the transfer was
    the compressed container plus watermark top-up."""
    for name, rec in rungs.items():
        if rec["mode"] == "snapshot":
            assert rec["snapshot_installed"] == 1, name
            assert rec["snapshot_raw_bytes"] > 0, name
        else:
            assert rec["snapshot_installed"] == 0, name
            # pure delta replays the table over the change stream: ~2
            # changes per row (cell + create sentinel), with a margin
            # for the few versions the broadcast backlog delivers
            assert rec["delta_changes_received"] >= 1.5 * rec["rows"], name
    for name in ("sync-100k-quiet-snapshot", "sync-1000k-quiet-snapshot"):
        rec = rungs[name]
        assert rec["delta_changes_received"] < rec["rows"], name


def test_snapshot_beats_delta_on_large_rung(banked, rungs):
    """ISSUE 12 acceptance: snapshot bootstrap beats pure-delta wall
    time on the 1M rung, speedup recorded in-band and consistent with
    the rung walls it claims to summarize."""
    assert banked["large_rung_rows"] == 1_000_000
    speedup = banked["snapshot_vs_delta_speedup"]
    assert speedup > 1.0, speedup
    d = rungs["sync-1000k-quiet-delta"]["wall_to_converged_s"]
    s = rungs["sync-1000k-quiet-snapshot"]["wall_to_converged_s"]
    assert s < d
    assert abs(speedup - d / s) / speedup < 0.05, (speedup, d, s)


def test_1m_under_fire_converges(rungs):
    """ISSUE 12 acceptance: the cold node converges against the 1M-row
    table WITH concurrent write traffic, via the snapshot fast path."""
    rec = rungs["sync-1000k-fire-snapshot"]
    assert rec["rows"] == 1_000_000
    assert rec["fire"] and rec["converged"]
    assert rec["snapshot_installed"] == 1


def test_chaos_loop_closes_with_zero_divergence(banked):
    """ISSUE 12 acceptance: partition → heal → catch-up → converge,
    with the divergence detector opening exactly during the partition
    (episodes ≥ 1) and reporting ZERO divergence at the end (one view
    group, episode closed, replicas row-identical — the bench asserts
    table equality before banking)."""
    chaos = banked["chaos"]
    assert chaos["divergence_zero"] is True
    assert chaos["episodes"] >= 1
    assert chaos["final_groups"] == 1
    assert chaos["partition_writes"] > 0

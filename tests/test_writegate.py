"""3-class priority write lanes + interruptible transactions
(VERDICT r2 missing #8). Ref: `agent.rs:478-519`, `sqlite_pool/mod.rs`.
"""

import asyncio
import sqlite3
import time

import pytest

from corrosion_tpu.runtime.writegate import PriorityWriteGate, WritePriority


def test_priority_lane_overtakes_normal_queue():
    async def main():
        gate = PriorityWriteGate()
        order = []

        async def worker(name, lane, hold=0.0):
            async with gate.lane(lane):
                order.append(name)
                if hold:
                    await asyncio.sleep(hold)

        # occupy the gate, then queue: normal x3, low, THEN priority
        first = asyncio.ensure_future(
            worker("hold", WritePriority.NORMAL, hold=0.05)
        )
        await asyncio.sleep(0.01)
        tasks = [
            asyncio.ensure_future(worker(f"n{i}", WritePriority.NORMAL))
            for i in range(3)
        ]
        tasks.append(asyncio.ensure_future(worker("low", WritePriority.LOW)))
        await asyncio.sleep(0.01)
        tasks.append(
            asyncio.ensure_future(worker("prio", WritePriority.PRIORITY))
        )
        await asyncio.gather(first, *tasks)
        # the late-arriving priority write ran before every queued
        # normal/low writer; low ran last
        assert order[0] == "hold"
        assert order[1] == "prio", order
        assert order[-1] == "low", order

    asyncio.run(main())


def test_gate_fifo_within_lane_and_release_correctness():
    async def main():
        gate = PriorityWriteGate()
        order = []

        async def worker(i):
            async with gate:
                order.append(i)

        async with gate:
            tasks = [asyncio.ensure_future(worker(i)) for i in range(5)]
            await asyncio.sleep(0.01)
        await asyncio.gather(*tasks)
        assert order == list(range(5))
        assert not gate.locked()

    asyncio.run(main())


def test_cancelled_waiter_does_not_leak_permit():
    async def main():
        gate = PriorityWriteGate()
        await gate.acquire()

        async def waiter():
            await gate.acquire(WritePriority.PRIORITY)

        t = asyncio.ensure_future(waiter())
        await asyncio.sleep(0.01)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        gate.release()
        # gate must be acquirable again promptly
        await asyncio.wait_for(gate.acquire(), 1.0)
        gate.release()

    asyncio.run(main())


def test_local_write_latency_bounded_under_apply_flood():
    """The starvation test: with the NORMAL lane saturated by simulated
    remote applies, a PRIORITY local write waits ~one apply, not the
    whole flood."""

    async def main():
        gate = PriorityWriteGate()
        apply_time = 0.02
        flood = 50

        async def remote_apply():
            async with gate.normal():
                await asyncio.sleep(apply_time)

        tasks = [asyncio.ensure_future(remote_apply()) for _ in range(flood)]
        await asyncio.sleep(apply_time / 2)  # flood in progress
        t0 = time.monotonic()
        async with gate.priority():
            latency = time.monotonic() - t0
        await asyncio.gather(*tasks)
        # bounded by ~the in-flight apply, far below flood * apply_time
        assert latency < 5 * apply_time, latency

    asyncio.run(main())


def test_interrupt_after_kills_stuck_statement(tmp_path):
    from corrosion_tpu.store.crdt import CrdtStore

    store = CrdtStore(str(tmp_path / "i.db"))
    store.apply_schema_sql("CREATE TABLE t (id INTEGER PRIMARY KEY, v TEXT);")
    # a pathological query: large cross join, far beyond 0.2s of work
    with pytest.raises(sqlite3.OperationalError, match="interrupt"):
        with store.interrupt_after(0.2):
            store._conn.execute(
                "WITH RECURSIVE c(x) AS (SELECT 1 UNION ALL SELECT x+1 FROM c)"
                " SELECT COUNT(*) FROM c LIMIT 1"
            ).fetchone()
    # the connection stays usable afterwards
    with store.write_tx(__import__("corrosion_tpu.types.base", fromlist=["Timestamp"]).Timestamp.now()) as tx:
        tx.execute("INSERT INTO t (id, v) VALUES (1, 'ok')")
    assert store._conn.execute("SELECT COUNT(*) FROM t").fetchone()[0] == 1
    store.close()

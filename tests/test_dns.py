"""Bootstrap resolution incl. the `host:port@dns_server` syntax
(bootstrap.rs:60-156), with a local canned-response DNS server."""

import asyncio
import socket
import struct

from corrosion_tpu.net.dns import (
    QTYPE_A,
    QTYPE_AAAA,
    decode_answers,
    encode_query,
    query_server,
    resolve_bootstrap,
    resolve_entry,
    split_bootstrap,
)


def canned_response(query: bytes, ips) -> bytes:
    """Answer the single question in `query` with A/AAAA records."""
    qid = struct.unpack(">H", query[:2])[0]
    # copy the question section verbatim
    off = 12
    while query[off] != 0:
        off += 1 + query[off]
    question = query[12 : off + 5]
    qtype = struct.unpack(">H", query[off + 1 : off + 3])[0]
    answers = b""
    count = 0
    for ip in ips:
        if ":" in ip and qtype == QTYPE_AAAA:
            rdata = socket.inet_pton(socket.AF_INET6, ip)
        elif ":" not in ip and qtype == QTYPE_A:
            rdata = socket.inet_pton(socket.AF_INET, ip)
        else:
            continue
        answers += (
            b"\xc0\x0c"  # pointer to qname
            + struct.pack(">HHIH", qtype, 1, 60, len(rdata))
            + rdata
        )
        count += 1
    return (
        struct.pack(">HHHHHH", qid, 0x8180, 1, count, 0, 0)
        + question
        + answers
    )


class CannedDns(asyncio.DatagramProtocol):
    def __init__(self, ips):
        self.ips = ips

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        self.transport.sendto(canned_response(data, self.ips), addr)


async def start_dns(ips):
    loop = asyncio.get_event_loop()
    transport, _ = await loop.create_datagram_endpoint(
        lambda: CannedDns(ips), local_addr=("127.0.0.1", 0)
    )
    return transport, transport.get_extra_info("sockname")[1]


def test_split_bootstrap():
    assert split_bootstrap("h:1@9.9.9.9:53") == ("h:1", "9.9.9.9:53")
    assert split_bootstrap("h:1") == ("h:1", None)


def test_codec_roundtrip_via_canned_server():
    q = encode_query(7, "example.test", QTYPE_A)
    resp = canned_response(q, ["10.1.2.3", "10.4.5.6"])
    assert decode_answers(resp, 7, QTYPE_A) == ["10.1.2.3", "10.4.5.6"]


def test_query_server_and_custom_resolver_syntax():
    async def main():
        transport, port = await start_dns(["10.9.9.1", "fd00::1"])
        try:
            ips = await query_server("127.0.0.1", port, "db.test", QTYPE_A)
            assert ips == ["10.9.9.1"]
            ips6 = await query_server(
                "127.0.0.1", port, "db.test", QTYPE_AAAA
            )
            assert ips6 == ["fd00::1"]
            # full entry resolution through the custom server
            got = await resolve_entry(f"db.test:7000@127.0.0.1:{port}")
            assert got == ["10.9.9.1:7000", "[fd00::1]:7000"]
        finally:
            transport.close()

    asyncio.run(main())


def test_resolve_passthrough_forms():
    async def main():
        # plain ip:port untouched
        assert await resolve_entry("10.0.0.1:7000") == ["10.0.0.1:7000"]
        # opaque labels (in-memory transport) untouched
        assert await resolve_entry("node1") == ["node1"]
        # system-resolver path on a guaranteed name
        got = await resolve_entry("localhost:7000")
        assert "127.0.0.1:7000" in got or "[::1]:7000" in got
        # aggregate keeps order + skips failures
        got = await resolve_bootstrap(["10.0.0.1:7000", "node2"])
        assert got == ["10.0.0.1:7000", "node2"]

    asyncio.run(main())

"""Tests for runtime aux: Backoff iterator, LockRegistry/CountedRwLock,
Prometheus exposition server. Mirrors the reference's coverage of
`backoff.rs` and `agent.rs:707-1066` (CountedTokioRwLock)."""

import asyncio

import pytest

from corrosion_tpu.runtime.backoff import Backoff
from corrosion_tpu.runtime.locks import CountedRwLock, LockRegistry
from corrosion_tpu.runtime.metrics import Registry, serve_prometheus


def test_backoff_growth_and_caps():
    b = Backoff(min_interval=1.0, max_interval=15.0, factor=2.0,
                jitter=0.0, retries=6)
    vals = list(b)
    assert vals == [1.0, 2.0, 4.0, 8.0, 15.0, 15.0]


def test_backoff_jitter_bounds_and_seed():
    b = Backoff(min_interval=1.0, max_interval=100.0, factor=2.0,
                jitter=0.3, retries=10).with_seed(42)
    vals = list(b)
    base = 1.0
    for v in vals:
        assert base * 0.7 - 1e-9 <= v <= base * 1.3 + 1e-9
        base = min(base * 2.0, 100.0)
    # deterministic under the same seed
    assert vals == list(
        Backoff(min_interval=1.0, max_interval=100.0, factor=2.0,
                jitter=0.3, retries=10).with_seed(42)
    )


def test_backoff_infinite_when_retries_none():
    it = iter(Backoff(retries=None, jitter=0.0))
    for _ in range(50):
        next(it)  # never raises StopIteration


def test_backoff_full_jitter_bounds_and_spread():
    """r9 full-jitter mode (the announce/rejoin storm-breaker): each
    yield is uniform in [0, min(base, max)], base still ramps
    exponentially — so retriers spread over the whole window instead of
    firing in the same beat."""
    b = Backoff(min_interval=4.0, max_interval=64.0, factor=2.0,
                retries=8, mode="full").with_seed(7)
    vals = list(b)
    base = 4.0
    for v in vals:
        assert 0.0 <= v <= base + 1e-9
        base = min(base * 2.0, 64.0)
    # genuinely spread: not all draws collapse near the cap or floor
    assert len({round(v, 3) for v in vals}) > 4
    # deterministic under the same seed
    assert vals == list(
        Backoff(min_interval=4.0, max_interval=64.0, factor=2.0,
                retries=8, mode="full").with_seed(7)
    )
    # two DIFFERENT seeds (two healed nodes) desynchronize — the storm
    # property the deterministic doubling had
    other = list(
        Backoff(min_interval=4.0, max_interval=64.0, factor=2.0,
                retries=8, mode="full").with_seed(8)
    )
    assert vals != other


def test_backoff_unknown_mode_raises():
    import pytest as _pytest

    with _pytest.raises(ValueError):
        next(iter(Backoff(mode="nonsense")))


@pytest.mark.asyncio
async def test_rwlock_readers_shared_writer_exclusive():
    reg = LockRegistry()
    lock = CountedRwLock(reg, "bookie")
    order = []

    async def reader(i):
        async with lock.read(f"r{i}"):
            order.append(f"r{i}+")
            await asyncio.sleep(0.01)
            order.append(f"r{i}-")

    async def writer():
        async with lock.write("w"):
            order.append("w+")
            await asyncio.sleep(0.01)
            order.append("w-")

    await asyncio.gather(reader(1), reader(2), writer())
    # both readers overlap (enter before either exits), writer is exclusive
    wi = order.index("w+")
    assert order[wi + 1] == "w-"
    assert set(order[:2]) == {"r1+", "r2+"} or order[0] == "w+"


@pytest.mark.asyncio
async def test_registry_tracks_and_releases():
    reg = LockRegistry()
    lock = CountedRwLock(reg, "members")
    async with lock.write("apply"):
        snap = reg.snapshot()
        assert len(snap) == 1
        assert snap[0].label == "members:apply"
        assert snap[0].kind == "write"
        assert snap[0].state == "locked"
    assert reg.snapshot() == []


@pytest.mark.asyncio
async def test_registry_snapshot_orders_longest_held_first():
    reg = LockRegistry()
    m1 = reg.register("a", "read")
    reg.acquired(m1)
    await asyncio.sleep(0.01)
    m2 = reg.register("b", "read")
    reg.acquired(m2)
    snap = reg.snapshot(top=1)
    assert [m.label for m in snap] == ["a"]
    reg.release(m1)
    reg.release(m2)


@pytest.mark.asyncio
async def test_prometheus_exposition_server():
    import aiohttp

    reg = Registry()
    reg.counter("corro_test_total", kind="x").inc(3)
    runner = await serve_prometheus("127.0.0.1:0", reg)
    port = runner.addresses[0][1]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/metrics") as resp:
                assert resp.status == 200
                body = await resp.text()
        assert 'corro_test_total{kind="x"} 3' in body
    finally:
        await runner.cleanup()

"""HTTP API + client: transactions, queries, migrations, table_stats,
end-to-end over real TCP with full agents gossiping through MemNetwork.

Mirrors the reference's direct-handler tests
(`api/public/mod.rs:745,834,964`) and client round-trips.
"""

import asyncio

from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.client import ClientError, CorrosionApiClient
from corrosion_tpu.net.mem import MemNetwork

from tests.test_agent import (
    TEST_SCHEMA,
    boot,
    count_rows,
    wait_until,
)


async def boot_with_api(net, addr, bootstrap=()):
    agent = await boot(net, addr, bootstrap)
    api = ApiServer(agent)
    agent.config.api.bind_addr = ["127.0.0.1:0"]
    await api.start()
    return agent, api, CorrosionApiClient(api.addrs[0])


def test_transactions_and_queries_roundtrip():
    async def main():
        net = MemNetwork(seed=23)
        a, api_a, client = await boot_with_api(net, "agent-a")
        try:
            res = await client.execute(
                [
                    ["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "one"]],
                    ["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "two"]],
                ]
            )
            assert res["version"] == 1
            assert [r["rows_affected"] for r in res["results"]] == [1, 1]
            assert res["actor_id"] == str(a.actor_id)

            rows = await client.query_rows(
                ["SELECT id, text FROM tests ORDER BY id", []]
            )
            assert rows == [[1, "one"], [2, "two"]]

            events = [e async for e in client.query("SELECT * FROM tests")]
            assert events[0] == {"columns": ["id", "text"]}
            assert "eoq" in events[-1]

            # sqlite error surfaces as a 400 with error result
            try:
                await client.execute(["INSERT INTO nope VALUES (1)"])
                raise AssertionError("expected ClientError")
            except ClientError as e:
                assert e.status == 400
                assert "error" in e.body["results"][0]

            stats = await client.table_stats()
            assert stats["total_row_count"] == 2
            assert stats["invalid_tables"] == []

            # faithful rows_affected (r14): multi-row DML reports its
            # true count, no-match DML reports 0 (not an error, not a
            # collapsed -1), and named-param statements go through the
            # same counting path
            res = await client.execute(
                [
                    ["UPDATE tests SET text = 'both'", []],
                    ["UPDATE tests SET text = 'none' WHERE id = 99", []],
                    ["DELETE FROM tests WHERE id = 99", []],
                    [
                        "INSERT INTO tests (id, text) VALUES (:i, :t)",
                        {"i": 3, "t": "named"},
                    ],
                ]
            )
            assert [r["rows_affected"] for r in res["results"]] == [
                2, 0, 0, 1,
            ]
        finally:
            await client.close()
            await api_a.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_migrations_endpoint():
    async def main():
        net = MemNetwork(seed=29)
        a, api, client = await boot_with_api(net, "agent-a")
        try:
            await client.schema(
                [TEST_SCHEMA, "CREATE TABLE extras (k TEXT PRIMARY KEY, v);"]
            )
            assert "extras" in a.store.schema.tables
            await client.execute(
                [["INSERT INTO extras (k, v) VALUES (?, ?)", ["x", 1]]]
            )
            rows = await client.query_rows("SELECT k, v FROM extras")
            assert rows == [["x", 1]]

            # destructive migration refused
            try:
                await client.schema(["CREATE TABLE extras (k TEXT PRIMARY KEY);"])
                raise AssertionError("expected ClientError")
            except ClientError as e:
                assert e.status == 400
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_bearer_authz():
    async def main():
        net = MemNetwork(seed=31)
        a, api, _ = await boot_with_api(net, "agent-a")
        a.config.api.authz_bearer = "sekrit"
        addr = api.addrs[0]
        noauth = CorrosionApiClient(addr)
        try:
            try:
                await noauth.execute(["SELECT 1"])
                raise AssertionError("expected 401")
            except ClientError as e:
                assert e.status == 401
            withauth = CorrosionApiClient(addr, token="sekrit")
            rows = await withauth.query_rows("SELECT 1")
            assert rows == [[1]]
            await withauth.close()
        finally:
            await noauth.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_status_endpoint_serves_cluster_plane():
    """GET /v1/status (r7): the JSON snapshot must surface the device
    kernel telemetry accumulated by a PViewClusterSim in this process —
    the acceptance path: kernel lane → registry → status plane."""
    import aiohttp

    from corrosion_tpu.models.cluster import PViewClusterSim

    # populate the process-global registry the way an embedding agent
    # would: a simulation stepping + draining through stats()
    sim = PViewClusterSim(128, slots=32, feeds_per_tick=2, feed_entries=16)
    sim.step(3)
    sim.stats()

    async def main():
        net = MemNetwork(seed=41)
        a, api, client = await boot_with_api(net, "agent-a")
        try:
            async with aiohttp.ClientSession() as s:
                r = await s.get(f"http://{api.addrs[0]}/v1/status")
                assert r.status == 200
                body = await r.json()
            assert body["actor_id"] == str(a.actor_id)
            assert body["cluster"]["size"] >= 1
            assert "member_states" in body["cluster"]
            pv = body["kernel_events"]["pview"]
            assert pv["gossip_emitted"] > 0
            assert pv["merge_won"] > 0
            # phase gauges ride along (PViewClusterSim.step publishes)
            assert body["kernel_phase_seconds"]["pview"]["tick"] > 0
            assert set(body["loop"]) == {
                "lag_max_seconds", "tasks_alive", "monitor_ticks"
            }
            assert body["sync"]["server_permits_available"] == 3
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_flight_endpoint_serves_tick_resolved_frames():
    """GET /v1/flight (r8): the last-K per-tick frames stitched from the
    device ring — event deltas + census by tick, the tick-RESOLVED
    sibling of /v1/status's cumulative totals."""
    import aiohttp

    from corrosion_tpu.models.cluster import PViewClusterSim

    sim = PViewClusterSim(128, slots=32, feeds_per_tick=2, feed_entries=16)
    sim.step(6)
    sim.stats()  # drains the ring into the process-global recorder

    async def main():
        net = MemNetwork(seed=43)
        a, api, client = await boot_with_api(net, "agent-flight")
        try:
            async with aiohttp.ClientSession() as s:
                r = await s.get(
                    f"http://{api.addrs[0]}/v1/flight",
                    params={"window": 4, "kernel": "pview"},
                )
                assert r.status == 200
                body = await r.json()
                assert body["window"] == 4
                assert body["event_lanes"][0] == "gossip_emitted"
                assert "census_alive" in body["census_lanes"]
                frames = body["frames"]
                # the last 4 of the 6 ticks the sim ran, in tick order
                assert [f["tick"] for f in frames] == [2, 3, 4, 5]
                assert all(f["kernel"] == "pview" for f in frames)
                assert frames[-1]["census"]["census_alive"] == 128
                assert frames[-1]["events"]["gossip_emitted"] > 0
                assert frames[-1]["wall"] > 0
                r = await s.get(
                    f"http://{api.addrs[0]}/v1/flight",
                    params={"window": "bogus"},
                )
                assert r.status == 400
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_alerts_endpoint_local_and_cluster_scope():
    """GET /v1/alerts (r20): the local rule-state view over a live
    agent's engine — a synthetic store-fault burst walks the
    store-faults rule through pending→firing and the endpoint reports
    it (with /v1/status's census in agreement) — and ?scope=cluster
    merges a REMOTE node's digest-carried alerts from the observatory
    store."""
    import aiohttp

    from corrosion_tpu.runtime import tsdb as tsdb_mod
    from corrosion_tpu.runtime.alerts import AlertEngine
    from corrosion_tpu.runtime.config import AlertsConfig
    from corrosion_tpu.runtime.digest import NodeDigest, encode_digest
    from corrosion_tpu.runtime.metrics import METRICS

    async def main():
        net = MemNetwork(seed=47)
        a, api, client = await boot_with_api(net, "agent-a")
        # deterministic plumbing: hand the agent an engine over a
        # hand-driven TSDB (agent setup's ensure() may have adopted an
        # earlier test's sampler config — this test owns its own)
        db = tsdb_mod.MetricsTSDB(
            registry=METRICS, sample_interval_secs=0.01
        )
        # for_secs near-zero but WINDOWS wide: under full-suite load
        # the gap between sample and evaluate can exceed a tiny
        # scaled-down window, and an empty window reads as "no data"
        cfg = AlertsConfig(for_scale=1.0)
        cfg.rules = [{
            "name": "store-faults", "kind": "rate",
            "series": "corro.store.write.errors.total",
            "op": ">", "value": 0.5, "for_secs": 0.0,
            "window_secs": 30.0, "severity": "page",
        }]
        a.alerts = AlertEngine(tsdb=db, cfg=cfg, agent=a, registry=METRICS)
        try:
            async with aiohttp.ClientSession() as s:
                r = await s.get(f"http://{api.addrs[0]}/v1/alerts")
                assert r.status == 200
                body = await r.json()
            assert body["enabled"] and body["actor_id"] == str(a.actor_id)
            rules = {x["rule"]: x for x in body["rules"]}
            assert "store-faults" in rules and "slo-burn" in rules
            assert all(x["state"] == "ok" for x in rules.values())

            # synthetic sick disk: rate points for the store-faults rule
            # (retry loop — on a loaded 1-core host a single
            # sample/evaluate pair can straddle a deschedule)
            c = METRICS.counter(
                "corro.store.write.errors.total", kind="busy"
            )
            db.sample_once()
            deadline = asyncio.get_event_loop().time() + 10.0
            row = None
            while asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.02)
                c.inc(50.0)
                db.sample_once()
                a.alerts.evaluate()
                if "store-faults" in a.alerts.census()["firing"]:
                    break
            async with aiohttp.ClientSession() as s:
                r = await s.get(
                    f"http://{api.addrs[0]}/v1/alerts?history=0"
                )
                body = await r.json()
            row = next(
                x for x in body["rules"] if x["rule"] == "store-faults"
            )
            assert row["state"] == "firing"
            assert "history" not in body
            # /v1/status census agrees
            async with aiohttp.ClientSession() as s:
                r = await s.get(f"http://{api.addrs[0]}/v1/status")
                status = await r.json()
            assert "store-faults" in status["alerts"]["firing"]

            # cluster scope: a remote node's digest carries ITS alerts
            remote = NodeDigest(
                actor_id=b"\x42" * 16, seq=1, wall=1e12, view_hash=1,
                view_size=2,
                alerts=[{
                    "rule": "loop-lag", "severity": "warn",
                    "state": "firing", "since": 1e12, "value": 0.9,
                    "drill": False,
                }],
            )
            assert a.observatory.receive(encode_digest(remote)) is not None
            async with aiohttp.ClientSession() as s:
                r = await s.get(
                    f"http://{api.addrs[0]}/v1/alerts?scope=cluster"
                )
                cluster = await r.json()
            assert cluster["scope"] == "cluster"
            assert cluster["coverage"]["known"] >= 2
            assert "loop-lag" in cluster["rollup"]
            assert "store-faults" in cluster["rollup"]  # own digest rode
            ll = cluster["rollup"]["loop-lag"]
            assert ll["firing"] and not ll["drill"]
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_http_write_gossips_to_peer():
    async def main():
        net = MemNetwork(seed=37)
        a, api_a, client_a = await boot_with_api(net, "agent-a")
        b, api_b, client_b = await boot_with_api(
            net, "agent-b", bootstrap=["agent-a"]
        )
        try:
            assert await wait_until(
                lambda: a.membership.cluster_size == 2
                and b.membership.cluster_size == 2
            )
            await client_a.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [9, "via-http"]]]
            )
            assert await wait_until(lambda: count_rows(b) == 1)
            rows = await client_b.query_rows("SELECT text FROM tests")
            assert rows == [["via-http"]]
        finally:
            from corrosion_tpu.agent.run import shutdown

            for c in (client_a, client_b):
                await c.close()
            for api in (api_a, api_b):
                await api.stop()
            for ag in (a, b):
                await shutdown(ag)

    asyncio.run(main())


def test_query_timeout_param_interrupts():
    """?timeout= on /v1/queries interrupts overrunning statements
    (TimeoutParams, api/public/mod.rs:525, mod.rs:336) — surfaced as an
    NDJSON error event; the read conn stays usable for the next query."""

    async def main():
        net = MemNetwork(seed=31)
        a, api_a, client = await boot_with_api(net, "agent-q")
        try:
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
            )
            # a recursive CTE that spins far longer than the timeout
            slow = (
                "WITH RECURSIVE c(x) AS "
                "(SELECT 1 UNION ALL SELECT x+1 FROM c WHERE x < 300000000) "
                "SELECT count(*) FROM c"
            )
            events = [e async for e in client.query(slow, timeout=0.3)]
            assert any("error" in e for e in events), events
            err = next(e for e in events if "error" in e)
            assert "interrupt" in err["error"].lower()
            # pool conn survives the interrupt: a normal query works
            rows = await client.query_rows(["SELECT id FROM tests", []])
            assert rows == [[1]]
            # an execute within budget is unaffected by the param
            res = await client.execute(
                [["INSERT INTO tests (id, text) VALUES (2, 'y')"]],
                timeout=5.0,
            )
            assert res["results"][0]["rows_affected"] == 1
        finally:
            await client.close()
            await api_a.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())

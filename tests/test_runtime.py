"""Runtime primitives: tripwire, channels, config, metrics."""

import asyncio

import pytest

from corrosion_tpu.runtime.channels import ChannelClosed, bounded
from corrosion_tpu.runtime.config import Config, load_config
from corrosion_tpu.runtime.metrics import Registry
from corrosion_tpu.runtime.tripwire import Outcome, TaskTracker, Tripwire


def test_config_defaults_and_env_overrides():
    cfg = load_config(env={})
    assert cfg.perf.processing_queue_len == 20_000
    assert cfg.perf.apply_queue_len == 50
    assert cfg.perf.max_concurrent_applies == 5
    cfg = load_config(
        env={
            "CORRO_DB__PATH": "/tmp/x.db",
            "CORRO_GOSSIP__MAX_MTU": "1400",
            "CORRO_GOSSIP__PLAINTEXT": "false",
            "CORRO_PERF__SYNC_INTERVAL_MAX_SECS": "30.5",
            "CORRO_API__BIND_ADDR": "0.0.0.0:1234,0.0.0.0:1235",
        }
    )
    assert cfg.db.path == "/tmp/x.db"
    assert cfg.gossip.max_mtu == 1400  # Optional[int] coerced
    assert cfg.gossip.plaintext is False
    assert cfg.perf.sync_interval_max_secs == 30.5
    assert cfg.api.bind_addr == ["0.0.0.0:1234", "0.0.0.0:1235"]


def test_config_toml(tmp_path):
    p = tmp_path / "corro.toml"
    p.write_text(
        '[db]\npath = "/data/c.db"\n[gossip]\nbootstrap = ["a:1", "b:2"]\n'
        "[perf]\napply_queue_len = 99\n"
    )
    cfg = load_config(str(p))
    assert cfg.db.path == "/data/c.db"
    assert cfg.gossip.bootstrap == ["a:1", "b:2"]
    assert cfg.perf.apply_queue_len == 99


def test_metrics_registry():
    r = Registry()
    r.counter("x.count", kind="a").inc()
    r.counter("x.count", kind="a").inc(2)
    r.gauge("x.gauge").set(5)
    r.histogram("x.lat").observe(0.3)
    text = r.render_prometheus()
    assert 'x_count{kind="a"} 3.0' in text
    assert "x_gauge 5" in text
    assert "x_lat_count 1" in text


def test_metrics_label_values_escaped():
    """Prometheus text format 0.0.4: backslash, double quote and line
    feed in label VALUES must be escaped — a hostile table name or
    endpoint path must not corrupt the whole exposition (r7 satellite;
    the old renderer emitted them raw)."""
    r = Registry()
    r.counter("x.count", table='we"ird\ntbl\\v').inc()
    text = r.render_prometheus()
    assert 'x_count{table="we\\"ird\\ntbl\\\\v"} 1.0' in text
    # exactly one physical line for the sample (the \n stayed escaped)
    lines = [ln for ln in text.splitlines() if ln.startswith("x_count")]
    assert len(lines) == 1
    # snapshot() returns the raw (unescaped) labels (the registry's
    # own corro.metrics.series gauge rides along since r20)
    (row,) = [r_ for r_ in r.snapshot() if r_[0] == "counter"]
    assert row == ("counter", "x.count", {"table": 'we"ird\ntbl\\v'}, 1.0)


def test_metrics_cardinality_guard():
    """r20: a runaway label value must not grow the registry without
    bound — per-name label sets cap at Registry.max_label_sets, excess
    mints are refused TYPED (corro.metrics.cardinality.dropped.total)
    and handed a shared detached instrument, and the registry's own
    size rides corro.metrics.series."""
    r = Registry()
    r.max_label_sets = 16
    insts = [r.counter("runaway.series", pk=str(i)) for i in range(30)]
    # the first 16 label sets minted; the rest share ONE detached sink
    minted = {id(c) for c in insts[:16]}
    assert len(minted) == 16
    assert len({id(c) for c in insts[16:]}) == 1
    assert insts[16] not in insts[:16]
    # drops are typed per kind
    dropped = r.counter(
        "corro.metrics.cardinality.dropped.total", kind="counter"
    )
    assert dropped.value == 14
    # detached writes land nowhere visible: the exposition still holds
    # exactly the admitted label sets
    insts[20].inc(99)
    rows = [
        row for row in r.snapshot()
        if row[1] == "runaway.series"
    ]
    assert len(rows) == 16
    assert all(v == 0.0 for *_x, v in rows)
    # the series gauge tracks the registry's true size (admitted series
    # + the gauge itself + the drop counter)
    g = r.gauge("corro.metrics.series")
    with r._lock:
        expect = r._series_total_locked()
    assert g.value == expect
    # other kinds cap independently of counters but share the name pool
    hh = [r.histogram("runaway.hist", pk=str(i)) for i in range(20)]
    assert len({id(h) for h in hh[:16]}) == 16
    assert r.counter(
        "corro.metrics.cardinality.dropped.total", kind="histogram"
    ).value == 4


def test_metrics_instruments_are_thread_safe():
    """Counter.inc / Gauge.add / Histogram.observe are called from
    worker threads (agent_metrics.collect_once, simulation drivers)
    while the event loop mutates the same instruments: the += is a
    read-modify-write the GIL does NOT make atomic.  Two threads
    hammering each instrument must lose nothing (r7 satellite: each
    instrument now carries its own lock)."""
    import threading

    r = Registry()
    c = r.counter("t.count")
    g = r.gauge("t.gauge")
    h = r.histogram("t.lat")
    n = 20_000

    def hammer():
        for i in range(n):
            c.inc()
            g.add(1.0)
            h.observe(0.001 * (i % 7))

    threads = [threading.Thread(target=hammer) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 2 * n
    assert g.value == 2 * n
    assert h.count == 2 * n
    assert sum(h.counts) == 2 * n


def test_channel_send_recv_close():
    async def main():
        tx, rx = bounded(4, "test")
        await tx.send(1)
        assert tx.try_send(2)
        assert await rx.recv() == 1
        assert rx.try_recv() == 2
        # close wakes a blocked receiver
        async def consumer():
            items = []
            try:
                while True:
                    items.append(await rx.recv())
            except ChannelClosed:
                return items

        task = asyncio.create_task(consumer())
        await asyncio.sleep(0.01)
        await tx.send(3)
        tx.close()
        items = await asyncio.wait_for(task, 2.0)
        assert items == [3]
        with pytest.raises(ChannelClosed):
            await tx.send(4)

    asyncio.run(main())


def test_channel_backpressure():
    async def main():
        tx, rx = bounded(2, "bp")
        assert tx.try_send(1) and tx.try_send(2)
        assert not tx.try_send(3)  # full
        assert tx.capacity_left == 0

    asyncio.run(main())


def test_tripwire_preemptible():
    async def main():
        tw = Tripwire()

        async def slow():
            await asyncio.sleep(30)
            return "done"

        async def quick():
            return "fast"

        outcome, val = await tw.preemptible(quick())
        assert outcome is Outcome.COMPLETED and val == "fast"

        task = asyncio.create_task(tw.preemptible(slow()))
        await asyncio.sleep(0.01)
        tw.trip()
        outcome, val = await asyncio.wait_for(task, 2.0)
        assert outcome is Outcome.PREEMPTED and val is None
        assert tw.tripped

    asyncio.run(main())


def test_task_tracker():
    async def main():
        tracker = TaskTracker()
        done = []

        async def work(i):
            await asyncio.sleep(0.01)
            done.append(i)

        for i in range(5):
            tracker.spawn(work(i))
        assert tracker.pending == 5
        assert await tracker.wait_all(5.0)
        assert sorted(done) == [0, 1, 2, 3, 4]
        assert tracker.pending == 0

    asyncio.run(main())


def test_agent_metrics_collection(tmp_path):
    """The periodic metrics loop (metrics.rs:18-108 counterpart) produces
    per-table, gap/buffered and membership gauges from a live agent."""
    import asyncio

    from corrosion_tpu.agent.agent_metrics import collect_once
    from corrosion_tpu.agent.run import run, setup, shutdown
    from corrosion_tpu.runtime.config import Config
    from corrosion_tpu.runtime.metrics import METRICS

    async def main():
        cfg = Config()
        cfg.db.path = str(tmp_path / "m.db")
        cfg.gossip.bind_addr = "127.0.0.1:0"
        agent = await setup(cfg)
        agent.store.apply_schema_sql(
            "CREATE TABLE mt (id INTEGER PRIMARY KEY, v TEXT);"
        )
        await run(agent)
        collect_once(agent)
        await shutdown(agent)

    asyncio.run(main())
    exposition = METRICS.render_prometheus()
    for needle in (
        'corro_db_table_rows{table="mt"}',
        "corro_db_gaps_count",
        "corro_db_buffered_changes_rows",
        "corro_bookie_actors",
        "corro_gossip_cluster_size",
    ):
        assert needle in exposition, needle


def test_invariant_hooks():
    """Antithesis-style always/sometimes layer (SURVEY §4): strict mode
    raises, log mode counts, markers register."""
    import os

    import pytest as pt

    from corrosion_tpu.runtime import invariants as inv

    old = os.environ.get(inv._MODE_ENV)
    try:
        os.environ[inv._MODE_ENV] = "strict"
        assert inv.assert_always(True, "fine") is True
        with pt.raises(inv.InvariantViolation):
            inv.assert_always(False, "broken", {"k": 1})
        with pt.raises(inv.InvariantViolation):
            inv.assert_unreachable("nope")

        os.environ[inv._MODE_ENV] = "log"
        assert inv.assert_always(False, "soft") is False  # no raise

        inv.reset_sometimes()
        inv.assert_sometimes("covered")
        inv.assert_sometimes("not-this-one", condition=False)
        reg = inv.sometimes_registry()
        assert reg.get("covered") == 1
        assert "not-this-one" not in reg
    finally:
        if old is None:
            os.environ.pop(inv._MODE_ENV, None)
        else:
            os.environ[inv._MODE_ENV] = old


def test_invariants_hold_under_replication_workload(tmp_path):
    """Run a two-node replication workload under strict invariants: the
    woven assert_always sites must hold, and the sometimes markers must
    actually fire (the Antithesis coverage contract)."""
    import asyncio
    import os

    from corrosion_tpu.runtime import invariants as inv

    old = os.environ.get(inv._MODE_ENV)
    os.environ[inv._MODE_ENV] = "strict"
    inv.reset_sometimes()
    try:
        from tests.test_agent import (
            TEST_SCHEMA,
            boot,
            count_rows,
            insert,
            wait_until,
        )
        from corrosion_tpu.agent.run import shutdown
        from corrosion_tpu.net.mem import MemNetwork

        async def main():
            net = MemNetwork(seed=21)
            a = await boot(net, "inv-a")
            b = await boot(net, "inv-b", bootstrap=["inv-a"])
            try:
                assert await wait_until(
                    lambda: all(
                        ag.membership.cluster_size == 2 for ag in (a, b)
                    )
                )
                for i in range(5):
                    await insert(a, i, f"row{i}")
                assert await wait_until(lambda: count_rows(b) == 5)
            finally:
                for ag in (a, b):
                    await shutdown(ag)

        asyncio.run(main())
        fired = inv.sometimes_registry()
        assert fired.get("changes broadcast", 0) > 0, fired
    finally:
        if old is None:
            os.environ.pop(inv._MODE_ENV, None)
        else:
            os.environ[inv._MODE_ENV] = old


def test_loop_lag_monitor():
    """The tokio-metrics analog publishes lag/task gauges while running
    and drains promptly when the tripwire fires."""
    import asyncio

    from corrosion_tpu.runtime import loopmon
    from corrosion_tpu.runtime.metrics import METRICS
    from corrosion_tpu.runtime.tripwire import TaskTracker, Tripwire

    old_interval = loopmon.SAMPLE_INTERVAL
    loopmon.SAMPLE_INTERVAL = 0.02
    try:
        async def main():
            trip = Tripwire()
            tracker = TaskTracker()
            loopmon.start(tracker, trip)
            await asyncio.sleep(0.5)
            trip.trip()
            assert await tracker.wait_all(2.0)

        asyncio.run(main())
    finally:
        loopmon.SAMPLE_INTERVAL = old_interval
    reg = METRICS.render_prometheus()
    assert "corro_runtime_loop_ticks" in reg or "corro.runtime.loop.ticks" in reg
    assert "loop_lag" in reg.replace(".", "_") or "lag" in reg


def test_wait_progress_semantics():
    """The soak-wait primitive: succeeds on pred, tolerates slow but
    steady progress past the stall bound, fails fast on a true stall,
    and caps livelock (progress forever, pred never)."""
    import asyncio

    from tests.test_agent import wait_progress

    async def main():
        # pred already true
        assert await wait_progress(lambda: True, lambda: 0)

        # steady progress, pred turns true after > stall worth of wall
        state = {"n": 0}

        def prog():
            state["n"] += 1
            return state["n"]

        t0 = asyncio.get_event_loop().time()
        assert await wait_progress(
            lambda: asyncio.get_event_loop().time() - t0 > 0.4,
            prog, stall=0.15, step=0.02,
        )

        # true stall: frozen progress fails after ~stall, well under cap
        t0 = asyncio.get_event_loop().time()
        assert not await wait_progress(
            lambda: False, lambda: 42, stall=0.2, cap=30.0, step=0.02
        )
        assert asyncio.get_event_loop().time() - t0 < 2.0

        # livelock: progress keeps changing, cap bounds the wait
        t0 = asyncio.get_event_loop().time()
        assert not await wait_progress(
            lambda: False, prog, stall=5.0, cap=0.3, step=0.02
        )
        assert asyncio.get_event_loop().time() - t0 < 2.0

        # scheduler starvation is NOT a stall (r5: the coexistence soak
        # flaked when a loaded host froze the whole process past the
        # stall bound): block the event loop synchronously for > stall;
        # progress is still at its pre-freeze value at the first
        # post-freeze poll (nothing ran during the freeze) and resumes
        # two polls later.  The old wall-clock silence check tripped at
        # that first poll; the compensated clock charges the freeze one
        # step and sees the resumed headway.
        import time as _time

        state["phase"] = 0

        def pred3():
            state["phase"] += 1
            if state["phase"] == 1:
                _time.sleep(0.5)  # whole-process freeze >> stall
            return state["phase"] >= 5

        def prog3():
            return state["phase"] if state["phase"] >= 3 else 0

        assert await wait_progress(
            pred3, prog3, stall=0.2, cap=30.0, step=0.02
        ), "a monitor freeze longer than stall was charged as silence"

    asyncio.run(main())

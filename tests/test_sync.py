"""Sync set-algebra tests, mirroring the reference's unit scenarios
(`klukai-types/src/sync.rs:542-817` exercises compute_available_needs over
heads/needs/partials combinations)."""

from corrosion_tpu.store.bookkeeping import (
    Bookie,
    NULL_GAP_STORE,
    PartialVersion,
)
from corrosion_tpu.sync import (
    chunk_range,
    compute_available_needs,
    generate_sync,
    state_need_len,
)
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.codec import NeedFull, NeedPartial, SyncState
from corrosion_tpu.types.rangeset import RangeSet

ME = ActorId(b"\x01" * 16)
PEER = ActorId(b"\x02" * 16)
ORIGIN = ActorId(b"\x03" * 16)


def st(actor, heads=None, need=None, partial=None):
    return SyncState(
        actor_id=actor,
        heads=heads or {},
        need=need or {},
        partial_need=partial or {},
    )


def test_missing_everything():
    ours = st(ME)
    theirs = st(PEER, heads={ORIGIN: 10})
    needs = compute_available_needs(ours, theirs)
    assert needs == {ORIGIN: [NeedFull((1, 10))]}


def test_head_catchup():
    ours = st(ME, heads={ORIGIN: 6})
    theirs = st(PEER, heads={ORIGIN: 10})
    needs = compute_available_needs(ours, theirs)
    assert needs == {ORIGIN: [NeedFull((7, 10))]}


def test_no_needs_when_equal():
    ours = st(ME, heads={ORIGIN: 10})
    theirs = st(PEER, heads={ORIGIN: 10})
    assert compute_available_needs(ours, theirs) == {}


def test_skip_own_actor_and_zero_heads():
    ours = st(ME)
    theirs = st(PEER, heads={ME: 10, ORIGIN: 0})
    assert compute_available_needs(ours, theirs) == {}


def test_gap_intersected_with_their_haves():
    # we need 3..8; they have 1..10 except their own need 5..6
    ours = st(ME, heads={ORIGIN: 10}, need={ORIGIN: [(3, 8)]})
    theirs = st(PEER, heads={ORIGIN: 10}, need={ORIGIN: [(5, 6)]})
    needs = compute_available_needs(ours, theirs)
    assert needs == {ORIGIN: [NeedFull((3, 4)), NeedFull((7, 8))]}


def test_their_partial_excluded_from_full_haves():
    ours = st(ME, heads={ORIGIN: 10}, need={ORIGIN: [(4, 6)]})
    theirs = st(
        PEER, heads={ORIGIN: 10}, partial={ORIGIN: {5: [(0, 3)]}}
    )
    needs = compute_available_needs(ours, theirs)
    # version 5 is partial on their side → only 4 and 6 are requestable
    assert needs == {ORIGIN: [NeedFull((4, 4)), NeedFull((6, 6))]}


def test_partial_when_they_have_it_fully():
    ours = st(
        ME, heads={ORIGIN: 10}, partial={ORIGIN: {7: [(3, 9)]}}
    )
    theirs = st(PEER, heads={ORIGIN: 10})
    needs = compute_available_needs(ours, theirs)
    assert needs == {ORIGIN: [NeedPartial(7, ((3, 9),))]}


def test_partial_intersection_when_both_partial():
    # we miss seqs 2..8 of version 7; they miss 6..9 → they can serve 2..5
    ours = st(ME, heads={ORIGIN: 10}, partial={ORIGIN: {7: [(2, 8)]}})
    theirs = st(PEER, heads={ORIGIN: 10}, partial={ORIGIN: {7: [(6, 9)]}})
    needs = compute_available_needs(ours, theirs)
    assert needs == {ORIGIN: [NeedPartial(7, ((2, 5),))]}


def test_both_partial_disjoint_is_empty():
    ours = st(ME, heads={ORIGIN: 10}, partial={ORIGIN: {7: [(0, 4)]}})
    theirs = st(PEER, heads={ORIGIN: 10}, partial={ORIGIN: {7: [(0, 5)]}})
    assert compute_available_needs(ours, theirs) == {}


def test_generate_sync_from_bookie():
    bookie = Bookie()
    with bookie.ensure(ORIGIN).write() as bv:
        snap = bv.snapshot()
        snap.insert_db(NULL_GAP_STORE, RangeSet([(1, 4), (8, 10)]))
        bv.commit_snapshot(snap)
        bv.insert_partial(
            9, PartialVersion(seqs=RangeSet([(0, 2)]), last_seq=9, ts=Timestamp(1))
        )
    state = generate_sync(bookie, ME)
    assert state.heads == {ORIGIN: 10}
    assert state.need == {ORIGIN: [(5, 7)]}
    assert state.partial_need == {ORIGIN: {9: [(3, 9)]}}
    assert state_need_len(state) == 3


def test_roundtrip_two_nodes_converge_needs():
    # A has 1..10 complete; B has nothing; B's needs against A cover 1..10
    bookie_a = Bookie()
    with bookie_a.ensure(ORIGIN).write() as bv:
        snap = bv.snapshot()
        snap.insert_db(NULL_GAP_STORE, RangeSet([(1, 10)]))
        bv.commit_snapshot(snap)
    sa = generate_sync(bookie_a, ME)
    sb = generate_sync(Bookie(), PEER)
    needs = compute_available_needs(sb, sa)
    assert needs == {ORIGIN: [NeedFull((1, 10))]}
    # and A needs nothing from B
    assert compute_available_needs(sa, sb) == {}


def test_chunk_range():
    assert chunk_range(1, 25, 10) == [(1, 10), (11, 20), (21, 25)]
    assert chunk_range(5, 5, 10) == [(5, 5)]


# -- r3: adaptive chunk sizing + streaming serve (peer/mod.rs:444-447,808-869)


def test_adaptive_chunk_policy():
    from corrosion_tpu.agent.syncer import (
        ADAPT_SLOW_SEND_S,
        CHUNK_TARGET_FLOOR,
        CHUNK_TARGET_MAX,
        AdaptiveChunkSize,
    )

    a = AdaptiveChunkSize()
    assert a.target == CHUNK_TARGET_MAX
    # slow sends halve…
    a.observe(ADAPT_SLOW_SEND_S + 0.1)
    assert a.target == CHUNK_TARGET_MAX // 2
    a.observe(ADAPT_SLOW_SEND_S + 0.1)
    assert a.target == CHUNK_TARGET_MAX // 4
    # …down to the 1 KiB floor
    for _ in range(10):
        a.observe(10.0)
    assert a.target == CHUNK_TARGET_FLOOR
    # fast sends grow ×1.5 back up to the 8 KiB cap
    a.observe(0.01)
    assert a.target == int(CHUNK_TARGET_FLOOR * 1.5)
    for _ in range(20):
        a.observe(0.01)
    assert a.target == CHUNK_TARGET_MAX


def test_chunk_changes_consults_target_per_chunk():
    from corrosion_tpu.types.change import Change, chunk_changes

    changes = [
        Change(
            table="t", pk=b"\x01", cid="v", val="x" * 100, col_version=1,
            db_version=1, seq=i, site_id=b"\x00" * 16, cl=1,
            ts=Timestamp(0),
        )
        for i in range(30)
    ]
    targets = iter([200, 200, 10_000, 10_000, 10_000, 10_000, 10_000])
    current = {"t": 200}

    def fn():
        current["t"] = next(targets, current["t"])
        return current["t"]

    chunks = list(chunk_changes(changes, last_seq=29, max_bytes_fn=fn))
    # first chunks were cut at the small target, later ones at the large
    sizes = [len(c) for c, _ in chunks]
    assert sizes[0] < sizes[-1]
    # seq coverage still contiguous to last_seq
    assert chunks[0][1][0] == 0
    for (_, (s1, e1)), (_, (s2, _)) in zip(chunks, chunks[1:]):
        assert s2 == e1 + 1
    assert chunks[-1][1][1] == 29


def test_changes_for_versions_streams_lazily(tmp_path):
    """The serve path must not materialize every requested version:
    pulling one version off the iterator touches only that version's
    rows (bounded memory on a large sync)."""
    from corrosion_tpu.store.crdt import CrdtStore

    store = CrdtStore(str(tmp_path / "s.db"))
    store.apply_schema_sql("CREATE TABLE tt (id INTEGER PRIMARY KEY, v TEXT);")
    n_versions = 30
    for i in range(n_versions):
        with store.write_tx(Timestamp.now()) as tx:
            tx.execute(
                "INSERT OR REPLACE INTO tt (id, v) VALUES (?, ?)", (i, f"v{i}")
            )

    conn = store.read_conn()
    row_queries = {"n": 0}

    def trace(sql):
        if "JOIN" in sql:  # the per-version row fetch
            row_queries["n"] += 1

    conn.set_trace_callback(trace)
    gen = store.changes_for_versions(store.site_id, 1, n_versions, conn=conn)
    first = next(gen)
    assert first[0] == n_versions  # newest first (db_version DESC)
    # only ONE version's rows were fetched so far (1 table → 1 JOIN query)
    assert row_queries["n"] == 1, row_queries
    rest = list(gen)
    assert len(rest) == n_versions - 1
    conn.close()
    store.close()

"""Devcluster tests: topology parsing, in-process convergence + broadcast
latency measurement, subprocess cluster. Mirrors klukai-devcluster plus
the BASELINE measurement harness."""

import os
import sys
import time
from pathlib import Path

import pytest

from corrosion_tpu.agent.membership import SwimConfig
from corrosion_tpu.devcluster import (
    DevCluster,
    ProcessCluster,
    Topology,
    TopologyError,
)
from corrosion_tpu.net.mem import MemNetwork

TEST_SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
)

FAST_SWIM = SwimConfig(probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0)


def test_topology_parse():
    topo = Topology.parse(
        """
        # a comment
        A -> B
        B -> C
        A -> C
        """
    )
    assert topo.nodes() == ["A", "B", "C"]
    assert topo.edges["A"] == ["B", "C"]
    assert topo.edges["C"] == []
    assert topo.responders() == ["C"]
    assert topo.initiators() == ["A", "B"]


def test_topology_parse_dedup_and_errors():
    topo = Topology.parse("A -> B\nA -> B\n")
    assert topo.edges["A"] == ["B"]
    with pytest.raises(TopologyError):
        Topology.parse("A => B")
    with pytest.raises(TopologyError):
        Topology.parse("A ->")


async def test_in_process_cluster_converges_and_replicates():
    topo = Topology.parse("A -> C\nB -> C\n")
    cluster = DevCluster(
        topo, TEST_SCHEMA, network=MemNetwork(), swim_config=FAST_SWIM
    )
    await cluster.start()
    try:
        t = await cluster.wait_converged(timeout=20.0)
        assert t < 20.0
        assert cluster.membership_counts() == {"A": 3, "B": 3, "C": 3}

        lat = await cluster.measure_broadcast_latency(
            "A", "tests", 1, "hello", timeout=20.0
        )
        assert set(lat) == {"A", "B", "C"}
        assert all(v < 20.0 for v in lat.values())
    finally:
        await cluster.stop()


@pytest.mark.slow
def test_process_cluster_three_nodes(tmp_path):
    topo = Topology.parse("A -> C\nB -> C\n")
    cluster = ProcessCluster(topo, str(tmp_path), TEST_SCHEMA)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(Path(__file__).resolve().parent.parent)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cluster.start(env=env)
    try:
        cluster.wait_up(timeout=60.0)
        # all three admin sockets respond; membership converges to 3
        import asyncio

        from corrosion_tpu.admin import AdminClient

        async def counts():
            out = {}
            for name, path in cluster.admin_paths.items():
                async with AdminClient(path) as c:
                    r = await c.call(
                        {"cmd": "cluster", "sub": "membership-states"}
                    )
                    alive = [
                        s for s in r["json"][0] if s["state"] == "ALIVE"
                    ]
                    out[name] = len(alive)
            return out

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            c = asyncio.run(counts())
            if all(v == 3 for v in c.values()):
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"no convergence: {c}")
    finally:
        cluster.stop()

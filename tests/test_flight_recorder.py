"""Flight recorder (r8): the [ring_ticks, N_FLIGHT_LANES] per-tick ring
in both SWIM scan carries + the host timeline plane over it.

The ring's contract:
  1. conservation — the event-delta rows are an exact decomposition of
     the cumulative lane: over any window that fits the ring,
     sum(ring event rows) == cumulative-lane delta, BIT-exactly, on
     both kernels;
  2. wrap-around — past ring_ticks ticks, row j holds the frame of the
     newest tick ≡ j (mod ring_ticks): exactly the last ring_ticks
     frames survive, byte-identical to a deeper ring's tail;
  3. the census half is a point-in-time level (alive/suspect/down,
     inbox high-water, max incarnation) that tracks injected churn;
  4. host stitching (`runtime.records`) is cursor-correct: re-drains
     append nothing, device-overwritten ticks count as dropped, and
     incident dumps are valid JSON with every frame.

All device cases use the scanned `tick_n` at tiny shapes — unrolled
per-tick traces are a compile-time trap on the 1-core CI host.
"""

import json

import jax
import numpy as np
import pytest

from corrosion_tpu.ops import swim, swim_pview
from corrosion_tpu.runtime.metrics import (
    FLIGHT_CENSUS,
    FLIGHT_LANES,
    KERNEL_EVENTS,
    Registry,
)
from corrosion_tpu.runtime.records import (
    FlightRecorder,
    frames_from_ring,
)

N_EV = len(KERNEL_EVENTS)
CEN = {name: N_EV + i for i, name in enumerate(FLIGHT_CENSUS)}


def _run(module, params, state, ticks, seed=7):
    return module.tick_n(state, jax.random.PRNGKey(seed), params, ticks)


# ---------------------------------------------------------------------------
# conservation: sum(ring deltas) == cumulative delta, bit-exactly
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kernel", ["dense", "pview"])
def test_ring_conserves_cumulative_lane(kernel):
    if kernel == "dense":
        module = swim
        params = swim.SwimParams(n=48, loss=0.1, ring_ticks=16)
    else:
        module = swim_pview
        params = swim_pview.PViewParams(
            n=96, slots=32, loss=0.1, feeds_per_tick=2, feed_entries=16,
            ring_ticks=16,
        )
    state = module.init_state(params, jax.random.PRNGKey(0))
    # window 1: from boot (events start at zero) — whole ring vs totals
    state = _run(module, params, state, 12)
    ev_mid = np.asarray(state.events).copy()
    ring = np.asarray(state.ring)
    assert np.array_equal(ring[:, :N_EV].sum(axis=0), ev_mid)
    # window 2: exactly ring_ticks further ticks — the ring now holds
    # precisely that window's deltas, so its sum IS the cumulative delta
    state = _run(module, params, state, 16, seed=11)
    ring = np.asarray(state.ring)
    delta = np.asarray(state.events) - ev_mid
    assert np.array_equal(ring[:, :N_EV].sum(axis=0), delta)
    assert (ring[:, :N_EV] >= 0).all()  # deltas, not totals


# ---------------------------------------------------------------------------
# wrap-around: the last ring_ticks frames survive, bit-identical to a
# deeper ring's tail
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kernel", ["dense", "pview"])
def test_ring_wraparound_matches_deep_ring_tail(kernel):
    def mk(ring_ticks):
        if kernel == "dense":
            return swim, swim.SwimParams(n=32, loss=0.05,
                                         ring_ticks=ring_ticks)
        return swim_pview, swim_pview.PViewParams(
            n=64, slots=16, loss=0.05, feeds_per_tick=2, feed_entries=8,
            ring_ticks=ring_ticks,
        )

    ticks = 21  # > 2×8: the small ring wraps twice
    module, p_small = mk(8)
    _, p_deep = mk(32)
    s_small = _run(module, p_small, module.init_state(
        p_small, jax.random.PRNGKey(0)), ticks)
    s_deep = _run(module, p_deep, module.init_state(
        p_deep, jax.random.PRNGKey(0)), ticks)
    # ring depth must not perturb the trajectory (same rng stream)
    assert np.array_equal(s_small.events, s_deep.events)
    ring_s = np.asarray(s_small.ring)
    ring_d = np.asarray(s_deep.ring)
    # deep ring still holds every tick < 32: row j of the small ring
    # must equal the deep ring's frame for the newest tick ≡ j (mod 8)
    for tick, row in frames_from_ring(ring_s, ticks):
        assert tick >= ticks - 8
        assert np.array_equal(row, ring_d[tick]), f"tick {tick}"
    # stitching covers exactly the last 8 ticks, in order
    stitched = list(frames_from_ring(ring_s, ticks))
    assert [t for t, _ in stitched] == list(range(ticks - 8, ticks))


# ---------------------------------------------------------------------------
# census lanes track injected churn
# ---------------------------------------------------------------------------


def test_census_lanes_track_churn():
    params = swim.SwimParams(n=32, suspicion_ticks=3, ring_ticks=64)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    state = _run(swim, params, state, 8)
    ring = np.asarray(state.ring)
    assert ring[7, CEN["census_alive"]] == 32
    assert ring[7, CEN["census_down"]] == 0
    state = swim.set_alive(state, 5, False)
    state = swim.set_alive(state, 9, False)
    state = _run(swim, params, state, 20, seed=3)
    ring = np.asarray(state.ring)
    last = ring[(int(state.t) - 1) % params.ring_ticks]
    assert last[CEN["census_alive"]] == 30
    assert last[CEN["census_down"]] == 2
    # the cascade is visible tick-resolved: some tick carried open
    # suspicion timers, and inbox high-water stayed within the cap
    live = [row for _t, row in frames_from_ring(ring, int(state.t))]
    assert max(r[CEN["census_suspect"]] for r in live) > 0
    assert max(r[CEN["inbox_highwater"]] for r in live) <= (
        params.incoming_slots
    )


# ---------------------------------------------------------------------------
# host stitching: cursors, drops, window, incident dump
# ---------------------------------------------------------------------------


def _fake_drain(t: int, ring_ticks: int = 8):
    """Synthetic device drain: row j%R of a [R, L] ring holds frame
    `tick` encoded as tick in lane 0 and tick+100 in the last census
    lane — the host stitching layer only sees (ring, t), so these tests
    need no kernel run (the device half is pinned above)."""
    ring = np.zeros((ring_ticks, len(FLIGHT_LANES)), dtype=np.int32)
    for tick in range(max(0, t - ring_ticks), t):
        ring[tick % ring_ticks, 0] = tick
        ring[tick % ring_ticks, -1] = tick + 100
    return swim.FlightDrain(ring=ring, t=t)


def test_recorder_stitching_cursor_and_drop_accounting():
    reg = Registry()
    rec = FlightRecorder(capacity=256)
    assert rec.record_ring("dense", _fake_drain(5), since=0,
                           registry=reg) == 5
    # re-drain without stepping: nothing new
    assert rec.record_ring("dense", _fake_drain(5), since=5,
                           registry=reg) == 0
    # advance to t=17: 12 new ticks > ring 8 — only the last 8 stitch,
    # 4 were overwritten on device and count as dropped
    assert rec.record_ring("dense", _fake_drain(17), since=5,
                           registry=reg) == 8
    snap = {
        (name, tuple(sorted(labels.items()))): v
        for _k, name, labels, v in reg.snapshot()
    }
    assert snap[("corro.flight.frames.total",
                 (("kernel", "dense"),))] == 13
    assert snap[("corro.flight.frames.dropped",
                 (("kernel", "dense"),))] == 4
    frames = rec.window(100, kernel="dense")
    assert [f["tick"] for f in frames] == list(range(5)) + list(
        range(9, 17)
    )
    assert all(
        set(f["events"]) == set(KERNEL_EVENTS)
        and set(f["census"]) == set(FLIGHT_CENSUS)
        for f in frames
    )
    # frames carry the ring's values, keyed by lane name (_fake_drain
    # writes its sentinel into whatever the LAST census lane is)
    assert frames[-1]["events"]["gossip_emitted"] == 16
    assert frames[-1]["census"][FLIGHT_CENSUS[-1]] == 116
    # a second sim of the same kernel restarting at tick 0 still records
    # (the cursor is the CALLER's, not global per kernel)
    assert rec.record_ring("dense", _fake_drain(3), since=0,
                           registry=reg) == 3


def test_recorder_host_frames_and_window_filter():
    reg = Registry()
    rec = FlightRecorder(capacity=16)
    rec.record_host_frame("crdt_merge", {"decide_won": 3}, registry=reg)
    rec.record_host_frame("crdt_merge", {"decide_won": 1}, registry=reg)
    rec.record_ring("dense", _fake_drain(2), registry=reg)
    assert [f["tick"] for f in rec.window(10, kernel="crdt_merge")] == [0, 1]
    assert len(rec.window(10)) == 4
    assert len(rec.window(1)) == 1
    # bounded history: the deque caps at capacity
    for _ in range(40):
        rec.record_host_frame("crdt_merge", {"decide_won": 1},
                              registry=reg)
    assert len(rec.window(10_000)) == 16


def test_incident_dump_black_box(tmp_path, monkeypatch):
    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    reg = Registry()
    rec = FlightRecorder()
    assert rec.snapshot_incident("empty", registry=reg) is None  # no frames
    rec.record_ring("dense", _fake_drain(4), registry=reg)
    path = rec.snapshot_incident("invariant:test/name", registry=reg)
    assert path is not None and path.startswith(str(tmp_path))
    with open(path) as f:
        dump = json.load(f)
    assert dump["reason"] == "invariant:test/name"
    assert dump["lanes"] == list(FLIGHT_LANES)
    assert len(dump["frames"]) == 4
    assert dump["frames"][-1]["events"]["gossip_emitted"] == 3
    snap = {name: v for _k, name, _l, v in reg.snapshot()}
    assert snap["corro.flight.incidents.total"] == 1

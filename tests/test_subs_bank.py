"""Banked-record guard for SUBS_SCALE.json (r16 serving-plane round).

`scripts/bench_pubsub.py --scale --ab` banks the stream-count ladder —
1k/10k/100k concurrent NDJSON subscription streams on one node, shared
(k=10) and distinct queries, with the r10 per-stream drain-loop path
(`-pre`, fanout="queue") measured ADJACENT to the r16 coalesced writer
(`-post`) on every rung up to 10k.  This guard pins the artifact's
shape and the round's acceptance bars (ISSUE 11): full delivery at 10k
streams, dedupe ratio ≥ 100 on the shared rung, the 100k rung admitted
under admission control with the over-limit probe 503'd, and p99
deliver reported as the headline.

Margin discipline (r15 memory): this 1-core host's throughput drifts
±30% between runs — the bars below are deterministic counts (delivery,
dedupe, admission) and ABSOLUTE bounds with wide margins, never
pre/post wall-clock ratios.
"""

from __future__ import annotations

import json
import os

import pytest

PATH = os.path.join(os.path.dirname(__file__), "..", "SUBS_SCALE.json")

AB_RUNGS = ["subs-1000x10", "subs-1000x1000d", "subs-10000x10"]
POST_RUNGS = AB_RUNGS + ["subs-100000x10"]


@pytest.fixture(scope="module")
def banked() -> dict:
    with open(PATH) as f:
        return {r["rung"]: r for r in json.load(f)}


def test_ladder_banked_pre_and_post(banked):
    for rung in AB_RUNGS:
        assert f"{rung}-pre" in banked, f"missing {rung}-pre"
    for rung in POST_RUNGS:
        assert f"{rung}-post" in banked, f"missing {rung}-post"
    # the 100k baseline is deliberately absent: 100k drain-loop tasks
    # is the pathology the round removes, not a baseline worth banking
    assert "subs-100000x10-pre" not in banked


def test_records_are_sha_stamped(banked):
    for rung, rec in banked.items():
        sha = rec.get("code_sha")
        assert sha, f"{rung}: no code fingerprint"
        assert "corrosion_tpu/pubsub/fanout.py" in sha, rung
        assert all(v != "missing" for v in sha.values()), (rung, sha)
        assert rec.get("measured_at"), f"{rung}: no measured_at"


def test_full_delivery_on_every_writer_rung(banked):
    """Every stream drains its complete event feed — INCLUDING the
    100k-stream rung: admission control bounds entry, it never costs an
    admitted stream an event, and nothing is shed at benign client
    speeds."""
    for rung in POST_RUNGS:
        rec = banked[f"{rung}-post"]
        assert rec["events_delivered"] == rec["events_expected"], rung
        assert rec["streams_complete"] == rec["streams"], rung
        assert rec["shed"] == 0, rung


def test_dedupe_ratio_bar(banked):
    """ISSUE 11 bar: streams/matchers ≥ 100 at 10k×k=10 (measured
    1000 — the canonical-hash dedupe runs k matchers, period), and the
    distinct rung really does run one matcher per query with its fd-cap
    note recorded (no silent caps)."""
    rec = banked["subs-10000x10-post"]
    assert rec["dedupe_ratio"] >= 100, rec["dedupe_ratio"]
    assert rec["matchers"] == rec["queries"] == 10
    d = banked["subs-1000x1000d-post"]
    assert d["matchers"] == d["streams"] == 1000
    assert "capped" in d["distinct_cap_note"]


def test_100k_rung_under_admission_control(banked):
    """The 100k-stream asymptote rung: admitted at exactly the
    [subs] max_streams ceiling, the one-over probe rejected with the
    typed 503, and the p99 deliver headline recorded and bounded (the
    probe measured ~6 s for a 2M-event fan-in burst; 60 s is the
    never-stalled bound, not a perf claim)."""
    rec = banked["subs-100000x10-post"]
    assert rec["streams"] == 100_000
    assert rec["admission"]["max_streams"] == 100_000
    assert rec["admission"]["over_limit_probe_rejected"] is True
    assert rec["deliver_p99_s"] is not None
    assert rec["deliver_p99_s"] < 60.0, rec["deliver_p99_s"]


def test_per_event_server_cost_flat_vs_stream_count(banked):
    """The asymptote claim itself: matcher+writer seconds per delivered
    event must stay ~flat as streams grow 1k → 10k → 100k (measured
    0.9-3 µs everywhere; the 10× bound is the regression tripwire for
    an O(streams × batches) task/queue resurrection, far above host
    noise)."""
    costs = {
        rung: banked[f"{rung}-post"]["per_event_server_us"]
        for rung in ("subs-1000x10", "subs-10000x10", "subs-100000x10")
    }
    for rung, us in costs.items():
        assert 0 < us < 50, (rung, us)
    assert (
        costs["subs-100000x10"] <= 10 * max(1e-9, costs["subs-1000x10"])
    ), costs


def test_writer_path_actually_measured_against_queue_path(banked):
    """A/B integrity: the pre rungs really ran the r10 drain-loop path
    and the post rungs the coalesced writer (the writer's round/walk
    instrumentation is the witness), with both sides delivering in
    full — the A/B compares equal work."""
    for rung in AB_RUNGS:
        pre, post = banked[f"{rung}-pre"], banked[f"{rung}-post"]
        assert pre["fanout"] == "queue" and post["fanout"] == "writer"
        assert pre["events_delivered"] == pre["events_expected"], rung
        assert post["writer_writes"] > 0, rung
        assert pre["writer_writes"] == 0, rung

"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip TPU hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly like the driver's dryrun (see __graft_entry__.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def rng_seed():
    return 42

"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip TPU hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly like the driver's dryrun (see __graft_entry__.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def rng_seed():
    return 42


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run `async def` tests with asyncio.run (no pytest-asyncio in image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None

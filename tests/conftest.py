"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip TPU hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly like the driver's dryrun (see __graft_entry__.py).

The image's TPU plugin can hang at backend init (see
corrosion_tpu/runtime/jaxenv.py), so tests unconditionally flip this
process to CPU — env JAX_PLATFORMS=axon must not leak into test runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

jaxenv.force_cpu_inprocess(n_devices=8)
# r20 tier-1 budget: share compiled kernel programs ACROSS tests and
# runs via the persistent XLA cache (jaxenv already uses it for the
# scale ladders).  The kernel suites recompile near-identical tick
# programs per distinct (shape, params) — the on-disk cache turns every
# repeat compile into a load (measured: the 8-device dryrun gate drops
# ~27 s of XLA compile on a warm cache; the suite's kernel-heavy files
# drop ~40-50 % each).  Cold first run pays the same compiles as before.
jaxenv.enable_compilation_cache()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def rng_seed():
    return 42


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run `async def` tests with asyncio.run (no pytest-asyncio in image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None

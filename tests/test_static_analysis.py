"""Tier-1 gate for corro-analyze (`corrosion_tpu/analysis/`).

Three layers, mirroring what the suite promises:

1. THE REPO IS CLEAN: every rule runs repo-wide against the committed
   `ANALYSIS_BASELINE.json` with no new findings and no stale baseline
   entries, in well under the 10 s budget.
2. EVERY CHECKER FIRES: per-rule seeded-violation fixtures — the
   true-positive snippet fails, the minimal fix passes, and a
   `# corro: noqa[rule]` comment suppresses (proving the whole
   driver-side filter chain, not just the checker).
3. THE FOLD IS LOSSLESS: the metrics lint folded into the framework
   still reports the same 236 literal series + 2 wildcard sites in both
   directions, and the `scripts/lint_metrics.py` shim keeps its API.

All pure-AST: no jax tracing, no sqlite, no network — the gate must
stay cheap (tier-1 runs near the 870 s kill).
"""

import json
import os
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from corrosion_tpu.analysis import (  # noqa: E402
    AnalysisContext,
    run_analysis,
)
from corrosion_tpu.analysis.blocking import AsyncBlockingChecker  # noqa: E402
from corrosion_tpu.analysis.capture_parity import (  # noqa: E402
    CaptureParityChecker,
)
from corrosion_tpu.analysis.codecext import CodecExtChecker  # noqa: E402
from corrosion_tpu.analysis.finalize_parity import (  # noqa: E402
    FinalizeParityChecker,
)
from corrosion_tpu.analysis.lockcheck import (  # noqa: E402
    LockDisciplineChecker,
)
from corrosion_tpu.analysis.metricsdoc import MetricsDocChecker  # noqa: E402
from corrosion_tpu.analysis.parity import LaneParityChecker  # noqa: E402
from corrosion_tpu.analysis.purity import KernelPurityChecker  # noqa: E402
from corrosion_tpu.analysis.actuators import (  # noqa: E402
    ActuatorDisciplineChecker,
)
from corrosion_tpu.analysis.profiler_safety import (  # noqa: E402
    ProfilerSafetyChecker,
)
from corrosion_tpu.analysis.timeouts import (  # noqa: E402
    TimeoutDisciplineChecker,
)


def _write(root, rel, body):
    path = os.path.join(str(root), rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(textwrap.dedent(body))
    return rel


# -- 1. the repo itself -----------------------------------------------------


def test_repo_runs_clean_against_baseline():
    t0 = time.monotonic()
    result = run_analysis(AnalysisContext(REPO))
    elapsed = time.monotonic() - t0
    assert result.new == [], "\n".join(f.render() for f in result.new)
    assert result.stale_keys == [], result.stale_keys
    # the CI/tooling satellite: the whole ≥6-rule pass stays cheap
    assert elapsed < 10.0, f"corro-analyze took {elapsed:.1f}s (budget 10s)"


def test_driver_cli_is_clean_and_fast():
    import corro_lint

    assert corro_lint.main([]) == 0
    assert corro_lint.main(["--rules", "metrics-doc"]) == 0
    assert corro_lint.main(["--rules", "nonsense"]) == 2


def test_baseline_file_is_committed_and_justified():
    with open(os.path.join(REPO, "ANALYSIS_BASELINE.json")) as f:
        data = json.load(f)
    assert data["version"] == 1
    for e in data["entries"]:
        assert e.get("justification"), f"unjustified baseline entry {e}"
        assert "UNREVIEWED" not in e["justification"], e


# -- 2. kernel-purity -------------------------------------------------------

_PURE_KERNEL = """
    import functools
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("params",))
    def tick_impl(state, rng, params):
        mask = jnp.greater(state, 0)
        if params.fancy:              # static branch: fine
            extra = jnp.sum(mask)
        else:
            extra = jnp.int32(0)
        return jnp.where(mask, state + extra, state)
"""

_IMPURE_KERNEL = """
    import functools
    import time
    import numpy as np
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("params",))
    def tick_impl(state, rng, params):
        t0 = time.monotonic()
        host = np.asarray(state)
        total = float(jnp.sum(state))
        peek = state.sum().item()
        if jnp.any(state > 0):
            state = state + 1
        mask = jnp.greater(state, 0)
        while mask.all():
            break
        return state
"""


def test_kernel_purity_fires_on_seeded_violations(tmp_path):
    rel = _write(tmp_path, "ops/kern.py", _IMPURE_KERNEL)
    ctx = AnalysisContext(str(tmp_path))
    fs = KernelPurityChecker(scope=("ops",)).run(ctx)
    msgs = "\n".join(f.message for f in fs)
    assert any("time." in f.message for f in fs), msgs
    assert any("numpy" in f.message for f in fs), msgs
    assert any("float()" in f.message for f in fs), msgs
    assert any(".item()" in f.message for f in fs), msgs
    assert any("`if`" in f.message for f in fs), msgs
    assert any("`while`" in f.message for f in fs), msgs
    assert all(f.path == rel and f.symbol == "tick_impl" for f in fs)


def test_kernel_purity_minimal_fix_passes(tmp_path):
    _write(tmp_path, "ops/kern.py", _PURE_KERNEL)
    ctx = AnalysisContext(str(tmp_path))
    assert KernelPurityChecker(scope=("ops",)).run(ctx) == []


def test_kernel_purity_ignores_host_wrappers(tmp_path):
    # the un-jitted drain next to the kernel may do host work freely
    _write(
        tmp_path,
        "ops/kern.py",
        _PURE_KERNEL
        + """
    def stats_and_events(state):
        import numpy as np
        return float(np.asarray(state).sum())
""",
    )
    ctx = AnalysisContext(str(tmp_path))
    assert KernelPurityChecker(scope=("ops",)).run(ctx) == []


def test_kernel_purity_noqa_suppresses(tmp_path):
    body = _IMPURE_KERNEL.replace(
        "peek = state.sum().item()",
        "peek = state.sum().item()  # corro: noqa[kernel-purity]",
    )
    _write(tmp_path, "ops/kern.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [KernelPurityChecker(scope=("ops",))], baseline={}
    )
    assert any(".item()" in f.message for f in result.suppressed)
    assert not any(".item()" in f.message for f in result.new)
    assert result.new  # the other violations still fail


# -- 3. lane-parity ---------------------------------------------------------


def _parity_fixture(
    tmp_path,
    pview_lane="lhm",
    pview_dtype="jnp.int32",
    mesh_names='"events"',
    extra_dense_lane="",
):
    dense_ring_init = (
        "ring=jnp.zeros((8, 4), dtype=jnp.int32),"
        if extra_dense_lane
        else ""
    )
    _write(
        tmp_path,
        "ops/swim.py",
        f"""
        import jax
        import jax.numpy as jnp
        from corrosion_tpu.runtime.metrics import FLIGHT_CENSUS, KERNEL_EVENTS

        class SwimState:
            t: jax.Array
            alive: jax.Array
            events: jax.Array
            lhm: jax.Array
            {extra_dense_lane}

        def _census_frame(n, alive):
            return jnp.stack([jnp.sum(alive), jnp.max(alive)])

        def _event_vector(**counts):
            return jnp.stack([counts[k] for k in KERNEL_EVENTS])

        def _init_state_impl(params, n):
            return SwimState(
                t=jnp.int32(0),
                alive=jnp.ones(n, dtype=bool),
                events=jnp.zeros(4, dtype=jnp.int32),
                lhm=jnp.zeros(n, dtype=jnp.int32),
                {dense_ring_init}
            )
        """,
    )
    _write(
        tmp_path,
        "ops/swim_pview.py",
        f"""
        import jax
        import jax.numpy as jnp
        from corrosion_tpu.ops.swim import _census_frame, _event_vector

        LANE_DTYPE = jnp.int16

        class PViewState:
            t: jax.Array
            alive: jax.Array
            events: jax.Array
            {pview_lane}: jax.Array

        def _init_impl(params, n):
            return PViewState(
                t=jnp.int32(0),
                alive=jnp.ones(n, dtype=bool),
                events=jnp.zeros(4, dtype=jnp.int32),
                {pview_lane}=jnp.zeros(n, dtype={pview_dtype}),
            )
        """,
    )
    _write(
        tmp_path,
        "mesh.py",
        f"""
        def _state_shardings(state, mesh):
            out = {{}}
            for name, arr in state._asdict().items():
                if getattr(arr, "ndim", 0) == 0 or name in ({mesh_names},):
                    out[name] = None
            return out
        """,
    )
    _write(
        tmp_path,
        "metrics.py",
        """
        KERNEL_EVENTS = ("a", "b", "c")
        FLIGHT_CENSUS = ("census_alive", "inc_max")
        FLIGHT_LANES = KERNEL_EVENTS + FLIGHT_CENSUS
        """,
    )
    return LaneParityChecker(
        dense="ops/swim.py",
        pview="ops/swim_pview.py",
        mesh="mesh.py",
        metrics="metrics.py",
    )


def test_lane_parity_clean_on_matching_kernels(tmp_path):
    checker = _parity_fixture(tmp_path)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_lane_parity_fires_on_name_drift(tmp_path):
    checker = _parity_fixture(tmp_path, pview_lane="lhm_score")
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("diverges" in f.message and "lhm" in f.message for f in fs)


def test_lane_parity_fires_on_dtype_drift(tmp_path):
    checker = _parity_fixture(tmp_path, pview_dtype="LANE_DTYPE")
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(
        "dtype diverges" in f.message and "int16" in f.message for f in fs
    )


def test_lane_parity_fires_on_unrouted_replicated_lane(tmp_path):
    # dense kernel grows a non-per-member `ring` lane that mesh.py's
    # by-name tuple does not replicate -> it would be member-sharded
    checker = _parity_fixture(
        tmp_path, extra_dense_lane="ring: jax.Array"
    )
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("ring" in f.message and "replicated" in f.message for f in fs)


def test_lane_parity_real_tree_is_clean():
    assert LaneParityChecker().run(AnalysisContext(REPO)) == []


# -- 4. async-blocking ------------------------------------------------------

_BLOCKING_ASYNC = """
    import asyncio
    import shutil
    import sqlite3
    import time
    from pathlib import Path

    async def handler(conn, path):
        time.sleep(0.1)
        conn.execute("SELECT 1")
        sqlite3.connect("x.db")
        open(path).read()
        Path(path).read_text()
        shutil.rmtree(path)
"""

_ROUTED_ASYNC = """
    import asyncio
    import shutil
    import sqlite3
    import time
    from pathlib import Path

    async def handler(conn, path):
        def work():
            time.sleep(0.1)
            conn.execute("SELECT 1")
            sqlite3.connect("x.db")
            open(path).read()
            Path(path).read_text()
            shutil.rmtree(path)
        await asyncio.to_thread(work)
        await asyncio.sleep(0.1)
"""


def test_async_blocking_fires_on_seeded_violations(tmp_path):
    _write(tmp_path, "agent/loopy.py", _BLOCKING_ASYNC)
    ctx = AnalysisContext(str(tmp_path))
    fs = AsyncBlockingChecker(scope=("agent",)).run(ctx)
    msgs = "\n".join(f.message for f in fs)
    assert len(fs) == 6, msgs
    assert any("time.sleep" in m for m in msgs.splitlines())
    assert any(".execute" in f.message for f in fs)
    assert any("sqlite3.connect" in f.message for f in fs)
    assert any("open()" in f.message for f in fs)
    assert any("Path.read_text" in f.message for f in fs)
    assert any("rmtree" in f.message for f in fs)


def test_async_blocking_nested_thread_bodies_pass(tmp_path):
    # the SAME calls inside a nested sync def handed to to_thread are
    # exactly the repo's discipline — zero findings
    _write(tmp_path, "agent/loopy.py", _ROUTED_ASYNC)
    ctx = AnalysisContext(str(tmp_path))
    assert AsyncBlockingChecker(scope=("agent",)).run(ctx) == []


def test_async_blocking_import_resolution(tmp_path):
    # dataclasses.replace is not os.replace; asyncio.sleep is not
    # time.sleep even when it arrives via `from asyncio import sleep`
    _write(
        tmp_path,
        "agent/loopy.py",
        """
        from dataclasses import replace
        from asyncio import sleep

        async def handler(obj):
            await sleep(0.1)
            return replace(obj, x=1)
        """,
    )
    ctx = AnalysisContext(str(tmp_path))
    assert AsyncBlockingChecker(scope=("agent",)).run(ctx) == []


def test_async_blocking_noqa_suppresses(tmp_path):
    body = _BLOCKING_ASYNC.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # corro: noqa[async-blocking]",
    )
    _write(tmp_path, "agent/loopy.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [AsyncBlockingChecker(scope=("agent",))], baseline={}
    )
    assert len(result.suppressed) == 1
    assert len(result.new) == 5


# -- 5. lock-discipline -----------------------------------------------------

_RACY_CLASS = """
    import asyncio

    class Store:
        def __init__(self):
            self.data = {}

        def rebuild(self):
            self.data["fresh"] = 1

        def on_packet(self, k, v):
            self.data[k] = v

        async def loop(self):
            await asyncio.to_thread(self.rebuild)
"""

_LOCKED_CLASS = """
    import asyncio
    import threading

    class Store:
        def __init__(self):
            self.data = {}
            self._lock = threading.Lock()

        def rebuild(self):
            with self._lock:
                self.data["fresh"] = 1

        def on_packet(self, k, v):
            with self._lock:
                self.data[k] = v

        async def loop(self):
            await asyncio.to_thread(self.rebuild)
"""


def test_lock_discipline_fires_on_thread_loop_race(tmp_path):
    _write(tmp_path, "pkg/store.py", _RACY_CLASS)
    ctx = AnalysisContext(str(tmp_path))
    fs = LockDisciplineChecker(scope=("pkg",)).run(ctx)
    assert len(fs) == 1
    assert "Store.data" in fs[0].message
    assert "rebuild" in fs[0].message


def test_lock_discipline_locked_fix_passes(tmp_path):
    _write(tmp_path, "pkg/store.py", _LOCKED_CLASS)
    ctx = AnalysisContext(str(tmp_path))
    assert LockDisciplineChecker(scope=("pkg",)).run(ctx) == []


def test_lock_discipline_async_name_collision_exempt(tmp_path):
    # another module to_threads a SYNC `close`; this class's `close` is
    # async (cannot be a to_thread target) and must not be swept in
    _write(
        tmp_path,
        "pkg/other.py",
        """
        import asyncio

        class Worker:
            def close(self):
                pass

        async def run(w):
            await asyncio.to_thread(w.close)
        """,
    )
    _write(
        tmp_path,
        "pkg/transport.py",
        """
        class Transport:
            def __init__(self):
                self.conns = {}

            async def close(self):
                self.conns.clear()

            def on_open(self, k, v):
                self.conns[k] = v
        """,
    )
    ctx = AnalysisContext(str(tmp_path))
    assert LockDisciplineChecker(scope=("pkg",)).run(ctx) == []


def test_lock_discipline_noqa_suppresses(tmp_path):
    body = _RACY_CLASS.replace(
        'self.data["fresh"] = 1',
        'self.data["fresh"] = 1  # corro: noqa[lock-discipline]',
    )
    _write(tmp_path, "pkg/store.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [LockDisciplineChecker(scope=("pkg",))], baseline={}
    )
    assert result.new == []
    assert len(result.suppressed) == 1


# -- 6. codec-ext -----------------------------------------------------------


def _codec_fixture(tmp_path, with_reader=True, with_test=True):
    reader = (
        """
    def decode_frame(data):
        if data and data[-1] >= _FRAME_EXT_V1:
            return data[:-1]
        return data
"""
        if with_reader
        else ""
    )
    _write(
        tmp_path,
        "codec.py",
        """
    _FRAME_EXT_V1 = 1

    def encode_frame(payload, ext=False):
        out = bytes(payload)
        if ext:
            out += bytes([_FRAME_EXT_V1])
        return out
"""
        + reader,
    )
    _write(
        tmp_path,
        "tests/test_codec.py",
        (
            """
    def test_frame_ext_old_new_compat():
        from codec import encode_frame
        assert encode_frame(b"x") == b"x"
"""
            if with_test
            else "\n"
        ),
    )
    return CodecExtChecker(
        codec_files=("codec.py",), test_files=("tests/test_codec.py",)
    )


def test_codec_ext_clean_when_exhaustive(tmp_path):
    checker = _codec_fixture(tmp_path)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_codec_ext_fires_on_missing_reader(tmp_path):
    checker = _codec_fixture(tmp_path, with_reader=False)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("no read path" in f.message for f in fs)


def test_codec_ext_fires_on_missing_compat_test(tmp_path):
    checker = _codec_fixture(tmp_path, with_test=False)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("compat pin is missing" in f.message for f in fs)


def test_codec_ext_real_tree_covers_all_gates():
    # _SWIM_EXT_V1 + _ENVELOPE_EXT_V1/V2 all have both directions and
    # compat tests today — and the checker actually saw them
    from corrosion_tpu.analysis.codecext import _gate_constants

    ctx = AnalysisContext(REPO)
    gates = {}
    for rel in CodecExtChecker().codec_files:
        gates.update(_gate_constants(ctx.file(rel).tree))
    assert {"_SWIM_EXT_V1", "_ENVELOPE_EXT_V1", "_ENVELOPE_EXT_V2"} <= set(
        gates
    )
    assert CodecExtChecker().run(ctx) == []


# -- 7. capture-parity ------------------------------------------------------

_TRIG_OK = """
    SENTINEL = "-1"

    class Store:
        def _create_triggers(self, t):
            name = t.name
            cols = "".join(f"({c})" for c in t.non_pk_cols)
            self._conn.execute(
                f'CREATE TRIGGER "{name}__crdt_ins" AFTER INSERT {cols}'
            )
            self._conn.execute(
                f'CREATE TRIGGER "{name}__crdt_upd" AFTER UPDATE'
                f" VALUES ('{name}', '{SENTINEL}X', NULL) {cols}"
            )
            self._conn.execute(
                f'CREATE TRIGGER "{name}__crdt_del" AFTER DELETE'
                f" VALUES ('{name}', '{SENTINEL}X', NULL)"
            )

        def _drop_triggers(self, name):
            for suffix in ("ins", "upd", "del"):
                self._conn.execute(f'DROP TRIGGER "{name}__crdt_{suffix}"')
"""

_CAP_OK = """
    SENTINEL = "-1"
    DELETE_MARKER = SENTINEL + "X"
    CAPTURED_KINDS = {"insert": "ins", "update": "upd", "delete": "del"}

    def _cells_insert(meta, vals):
        return [(c, vals.get(c)) for c in meta.non_pk_cols]

    def _cells_update(meta, old, new):
        return [(c, new[c]) for c in meta.non_pk_cols if c in new]

    def _cells_delete(meta):
        return [(DELETE_MARKER, None)]
"""


def _parity_capture_fixture(tmp_path, cap_body=_CAP_OK, trig_body=_TRIG_OK):
    _write(tmp_path, "store/crdt.py", trig_body)
    _write(tmp_path, "store/capture.py", cap_body)
    return CaptureParityChecker(
        crdt="store/crdt.py", capture="store/capture.py"
    )


def test_capture_parity_clean_when_lockstep(tmp_path):
    checker = _parity_capture_fixture(tmp_path)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_capture_parity_fires_on_uncovered_trigger_kind(tmp_path):
    body = _CAP_OK.replace(', "delete": "del"', "")
    checker = _parity_capture_fixture(tmp_path, cap_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("__crdt_del" in f.message for f in fs), fs


def test_capture_parity_fires_on_column_source_drift(tmp_path):
    body = _CAP_OK.replace(
        "[(c, new[c]) for c in meta.non_pk_cols if c in new]",
        "[(c, v) for c, v in new.items()]",
    )
    checker = _parity_capture_fixture(tmp_path, cap_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(
        "column" in f.message and "_cells_update" in f.message for f in fs
    ), fs


def test_capture_parity_fires_on_delete_marker_drift(tmp_path):
    body = _CAP_OK.replace(
        'DELETE_MARKER = SENTINEL + "X"', 'DELETE_MARKER = SENTINEL + "D"'
    )
    checker = _parity_capture_fixture(tmp_path, cap_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("delete-marker" in f.snippet for f in fs), fs


def test_capture_parity_fires_on_missing_cells_builder(tmp_path):
    body = _CAP_OK.replace("def _cells_update", "def _other_update")
    checker = _parity_capture_fixture(tmp_path, cap_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any("_cells_update" in f.message for f in fs), fs


def test_capture_parity_noqa_suppresses(tmp_path):
    body = _CAP_OK.replace(
        'CAPTURED_KINDS = {"insert": "ins", "update": "upd"}',
        "CAPTURED_KINDS = {}",
    ).replace(
        'CAPTURED_KINDS = {"insert": "ins", "update": "upd", "delete": "del"}',
        'CAPTURED_KINDS = {"insert": "ins", "update": "upd"}'
        "  # corro: noqa[capture-parity]",
    )
    checker = _parity_capture_fixture(tmp_path, cap_body=body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(ctx, [checker], baseline={})
    assert result.new == []
    assert result.suppressed, "the uncovered-kind finding must be noqa'd"


def test_capture_parity_real_tree_is_clean():
    assert CaptureParityChecker().run(AnalysisContext(REPO)) == []


# r21: the columnar finalize is a third consumer of the capture
# conventions — fixture crdt module carrying the finalize-side symbols
_FINALIZE_OK = _TRIG_OK + """

    def _dedupe_pending(pending):
        marker = SENTINEL + "X"
        return [p for p in pending if p[1] != marker]

    def _finalize_engine():
        return "columnar"

    def _phase_b_columnar(self, specs):
        cells = [s for s in specs if s[2] != SENTINEL]
        return write_change_cells(cells, b"site")
"""


def test_capture_parity_clean_with_columnar_finalize(tmp_path):
    checker = _parity_capture_fixture(tmp_path, trig_body=_FINALIZE_OK)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_capture_parity_fires_on_finalize_marker_drift(tmp_path):
    body = _FINALIZE_OK.replace(
        'marker = SENTINEL + "X"', 'marker = SENTINEL + "D"'
    )
    checker = _parity_capture_fixture(tmp_path, trig_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "finalize-marker-drift" for f in fs), fs
    assert all(f.path == "store/crdt.py" for f in fs), fs


def test_capture_parity_fires_on_columnar_encoder_drift(tmp_path):
    body = _FINALIZE_OK.replace(
        'return write_change_cells(cells, b"site")', "return cells"
    )
    checker = _parity_capture_fixture(tmp_path, trig_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "columnar-encoder-drift" for f in fs), fs


def test_capture_parity_fires_on_missing_columnar_builder(tmp_path):
    body = _FINALIZE_OK.replace(
        "def _phase_b_columnar", "def _phase_b_other"
    )
    checker = _parity_capture_fixture(tmp_path, trig_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "missing-columnar-builder" for f in fs), fs


# -- 7b. finalize-parity (r24 native engine <-> Python glue) ----------------

_NATIVE_CRDT_OK = """
    SENTINEL = "-1"
    _NATIVE_FINALIZE_ABI = 2
    _NATIVE_SENTINEL_CID = -1

    def _finalize_engine():
        return "native"

    class Store:
        def _phase_b_columnar(self, specs):
            return [s for s in specs if s[2] != SENTINEL]

        def _phase_b_native(self, specs):
            lib = finalize_batch_lib()
            if lib is None:
                METRICS.counter(
                    "corro.write.finalize.native.unavailable"
                ).inc()
                return self._phase_b_columnar(specs)
            cells = [s for s in specs if s[2] != SENTINEL]
            return write_change_cells(cells, b"site")
"""

_NATIVE_CPP_OK = """
    #define FINALIZE_ABI_VERSION 2
    constexpr int32_t FIN_CID_SENTINEL = -1;
    extern "C" int crdt_finalize_batch(int32_t n_items) {
      int64_t cl = 3;
      cl += (cl & 1);
      if (cl % 2 == 0) return 0;
      return 0;
    }
"""


def _finalize_parity_fixture(
    tmp_path, crdt_body=_NATIVE_CRDT_OK, cpp_body=_NATIVE_CPP_OK
):
    _write(tmp_path, "store/crdt.py", crdt_body)
    _write(tmp_path, "native/crdt_batch.cpp", cpp_body)
    return FinalizeParityChecker(
        crdt="store/crdt.py", cpp="native/crdt_batch.cpp"
    )


def test_finalize_parity_clean_when_lockstep(tmp_path):
    checker = _finalize_parity_fixture(tmp_path)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_finalize_parity_silent_when_no_native_engine(tmp_path):
    body = _NATIVE_CRDT_OK.replace('return "native"', 'return "columnar"')
    checker = _finalize_parity_fixture(tmp_path, crdt_body=body)
    assert checker.run(AnalysisContext(str(tmp_path))) == []


def test_finalize_parity_fires_on_abi_version_drift(tmp_path):
    body = _NATIVE_CPP_OK.replace(
        "#define FINALIZE_ABI_VERSION 2", "#define FINALIZE_ABI_VERSION 3"
    )
    checker = _finalize_parity_fixture(tmp_path, cpp_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "abi-version-drift" for f in fs), fs
    assert all(f.path == "native/crdt_batch.cpp" for f in fs), fs


def test_finalize_parity_fires_on_sentinel_id_drift(tmp_path):
    body = _NATIVE_CPP_OK.replace(
        "FIN_CID_SENTINEL = -1", "FIN_CID_SENTINEL = -2"
    )
    checker = _finalize_parity_fixture(tmp_path, cpp_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "sentinel-id-drift" for f in fs), fs


def test_finalize_parity_fires_on_missing_native_builder(tmp_path):
    body = _NATIVE_CRDT_OK.replace(
        "def _phase_b_native", "def _phase_b_other"
    )
    checker = _finalize_parity_fixture(tmp_path, crdt_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "missing-native-builder" for f in fs), fs


def test_finalize_parity_fires_on_missing_export(tmp_path):
    body = _NATIVE_CPP_OK.replace(
        'extern "C" int crdt_finalize_batch', "static int finalize_impl"
    )
    checker = _finalize_parity_fixture(tmp_path, cpp_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "missing-native-export" for f in fs), fs


def test_finalize_parity_fires_on_uncounted_fallback(tmp_path):
    body = _NATIVE_CRDT_OK.replace(
        """            if lib is None:
                METRICS.counter(
                    "corro.write.finalize.native.unavailable"
                ).inc()
                return self._phase_b_columnar(specs)
""",
        """            if lib is None:
                return self._phase_b_columnar(specs)
""",
    )
    checker = _finalize_parity_fixture(tmp_path, crdt_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "native-fallback-uncounted" for f in fs), fs


def test_finalize_parity_fires_on_dropped_fallback(tmp_path):
    body = _NATIVE_CRDT_OK.replace(
        "return self._phase_b_columnar(specs)", "raise RuntimeError(lib)"
    )
    checker = _finalize_parity_fixture(tmp_path, crdt_body=body)
    fs = checker.run(AnalysisContext(str(tmp_path)))
    assert any(f.snippet == "native-fallback-drift" for f in fs), fs


def test_finalize_parity_noqa_suppresses(tmp_path):
    body = _NATIVE_CRDT_OK.replace(
        "def _phase_b_native(self, specs):",
        "def _phase_b_native(self, specs):"
        "  # corro: noqa[finalize-parity]",
    ).replace(
        "return write_change_cells(cells, b\"site\")", "return cells"
    )
    checker = _finalize_parity_fixture(tmp_path, crdt_body=body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(ctx, [checker], baseline={})
    assert result.new == []
    assert result.suppressed, "the encoder-drift finding must be noqa'd"


def test_finalize_parity_real_tree_is_clean():
    assert FinalizeParityChecker().run(AnalysisContext(REPO)) == []


# -- 8. timeout-discipline --------------------------------------------------

_UNBOUNDED_NET_AWAITS = """
    async def session(stream, transport, addr):
        stream2 = await transport.open_bi(addr)
        await stream.send(b"hello")
        frame = await stream.recv()
        await transport.send_uni(addr, b"payload")
        await stream.finish()
        return frame
"""

_BOUNDED_NET_AWAITS = """
    import asyncio

    RECV_TIMEOUT = 10.0
    SEND_TIMEOUT = 30.0

    async def session(stream, transport, addr):
        stream2 = await asyncio.wait_for(
            transport.open_bi(addr), SEND_TIMEOUT
        )
        await asyncio.wait_for(stream.send(b"hello"), SEND_TIMEOUT)
        frame = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
        await asyncio.wait_for(
            transport.send_uni(addr, b"payload"), SEND_TIMEOUT
        )
        await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
        return frame
"""


def test_timeout_discipline_fires_on_seeded_violations(tmp_path):
    _write(tmp_path, "agent/sessions.py", _UNBOUNDED_NET_AWAITS)
    ctx = AnalysisContext(str(tmp_path))
    fs = TimeoutDisciplineChecker(scope=("agent",)).run(ctx)
    assert len(fs) == 5, "\n".join(f.message for f in fs)
    assert all("wrap in asyncio.wait_for" in f.message for f in fs)
    flagged = {f.snippet for f in fs}
    assert any(".recv()" in s for s in flagged)
    assert any("open_bi" in s for s in flagged)


def test_timeout_discipline_minimal_fix_passes(tmp_path):
    _write(tmp_path, "agent/sessions.py", _BOUNDED_NET_AWAITS)
    ctx = AnalysisContext(str(tmp_path))
    assert TimeoutDisciplineChecker(scope=("agent",)).run(ctx) == []


def test_timeout_discipline_exempts_channels_and_datagrams(tmp_path):
    # in-process channels (tx_/rx_, runtime/channels.py backpressure by
    # design) and UDP fire-and-forget datagrams are NOT peer waits
    _write(
        tmp_path,
        "agent/loops.py",
        """
        async def pump(agent, addr, data):
            item = await agent.rx_apply.recv()
            await agent.tx_bcast.send(item)
            await agent.transport.send_datagram(addr, data)
        """,
    )
    ctx = AnalysisContext(str(tmp_path))
    assert TimeoutDisciplineChecker(scope=("agent",)).run(ctx) == []


def test_timeout_discipline_noqa_suppresses(tmp_path):
    body = _UNBOUNDED_NET_AWAITS.replace(
        'await stream.send(b"hello")',
        'await stream.send(b"hello")  # corro: noqa[timeout-discipline]',
    )
    _write(tmp_path, "agent/sessions.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [TimeoutDisciplineChecker(scope=("agent",))], baseline={}
    )
    assert len(result.suppressed) == 1
    assert len(result.new) == 4


def test_timeout_discipline_real_tree_is_clean():
    """The zombie-node fix round (r18): every network await in agent/
    and api/ now carries a deadline — this pin keeps it that way."""
    assert TimeoutDisciplineChecker().run(AnalysisContext(REPO)) == []


# -- 9. actuator-discipline -------------------------------------------------

_DISCIPLINED_ACTUATOR = """
    from corrosion_tpu.chaos.faults import CENSUS
    from corrosion_tpu.runtime.records import FLIGHT

    async def _act_restart(agent):
        drill = CENSUS.snapshot()
        FLIGHT.record_host_frame("remediation", {"restart": 1})
        return {"drill": drill.get("scenario")}

    def registry(cfg):
        return {
            "restart": Actuator(
                name="restart", rule="loop-lag", summary="s",
                cooldown_secs=30.0, act=_act_restart,
            )
        }
"""

_SLOPPY_ACTUATORS = """
    from corrosion_tpu.chaos.faults import CENSUS
    from corrosion_tpu.runtime.records import FLIGHT

    async def _act_no_census(agent):
        FLIGHT.record_host_frame("remediation", {"x": 1})
        return {}

    async def _act_no_flight(agent):
        CENSUS.snapshot()
        return {}

    def registry(cfg):
        return {
            # no cooldown at all: flaps every supervisor tick
            "a": Actuator(name="a", rule="r", summary="s",
                          act=_act_no_census),
            # zero cooldown: same flap, dressed up
            "b": Actuator(name="b", rule="r", summary="s",
                          cooldown_secs=0, act=_act_no_flight),
            # lambda act: body invisible to the discipline scan
            "c": Actuator(name="c", rule="r", summary="s",
                          cooldown_secs=5.0, act=lambda agent: None),
        }
"""


def test_actuator_discipline_fires_on_seeded_violations(tmp_path):
    _write(tmp_path, "corrosion_tpu/agent/remed.py", _SLOPPY_ACTUATORS)
    ctx = AnalysisContext(str(tmp_path))
    fs = ActuatorDisciplineChecker().run(ctx)
    # a: no cooldown + act missing the CENSUS drill check;
    # b: non-positive cooldown + act missing the FLIGHT emit;
    # c: unresolvable lambda act
    assert len(fs) == 5, "\n".join(f.render() for f in fs)
    msgs = "\n".join(f.message for f in fs)
    assert "without cooldown_secs" in msgs
    assert "non-positive cooldown_secs=0" in msgs
    assert "CENSUS.snapshot" in msgs
    assert "FLIGHT.record_host_frame" in msgs
    assert "lambda/imported callable" in msgs


def test_actuator_discipline_minimal_fix_passes(tmp_path):
    _write(tmp_path, "corrosion_tpu/agent/remed.py", _DISCIPLINED_ACTUATOR)
    ctx = AnalysisContext(str(tmp_path))
    assert ActuatorDisciplineChecker().run(ctx) == []


def test_actuator_discipline_accepts_config_sourced_cooldown(tmp_path):
    # `cooldown_secs=cfg.sync_cooldown_secs` is the idiom in the real
    # registry — a non-literal expression is the config's contract,
    # not a violation
    body = _DISCIPLINED_ACTUATOR.replace(
        "cooldown_secs=30.0", "cooldown_secs=cfg.sync_cooldown_secs"
    )
    _write(tmp_path, "corrosion_tpu/agent/remed.py", body)
    ctx = AnalysisContext(str(tmp_path))
    assert ActuatorDisciplineChecker().run(ctx) == []


def test_actuator_discipline_ignores_out_of_scope_probes(tmp_path):
    # tests build synthetic probe actuators on purpose — only the
    # shipped tree is held to the discipline
    _write(tmp_path, "tests/test_probe.py", _SLOPPY_ACTUATORS)
    ctx = AnalysisContext(str(tmp_path))
    assert ActuatorDisciplineChecker().run(ctx) == []


def test_actuator_discipline_noqa_suppresses(tmp_path):
    body = _SLOPPY_ACTUATORS.replace(
        '"c": Actuator(name="c", rule="r", summary="s",',
        '"c": Actuator(  # corro: noqa[actuator-discipline]\n'
        '              name="c", rule="r", summary="s",',
    )
    _write(tmp_path, "corrosion_tpu/agent/remed.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [ActuatorDisciplineChecker()], baseline={}
    )
    assert len(result.suppressed) == 1
    assert len(result.new) == 4


def test_actuator_discipline_real_tree_is_clean():
    """The shipped registry (agent/remediation.py) carries the full
    discipline: positive config-sourced cooldowns, CENSUS drill checks
    and FLIGHT emits in every act body — this pin keeps it that way."""
    fs = ActuatorDisciplineChecker().run(AnalysisContext(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)


# -- 10. profiler-safety ----------------------------------------------------

_HOT_SAMPLER_SLOPPY = """
    import asyncio
    import json

    log = None
    METRICS = None


    class Ring:
        def add_sample(self, key):
            with self._map_lock:
                self.folded[key] = self.folded.get(key, 0) + 1


    class Sampler:
        def sample_once(self):
            loop = asyncio.get_event_loop()
            self._gate.acquire()
            key = f"{loop}"
            frames = [f for f in (1, 2)]
            top = sorted(frames)
            payload = json.dumps(key)
            log.debug("sampled %s", payload)
            METRICS.counter("x").inc()
            db = self.agent
            add = self.ring.add_sample
            add(key)
            self._flush_coldpath()

        def _flush_coldpath(self):
            # exempt by suffix: bounded by cadence, not sample rate
            with self._big_lock:
                return sorted(json.dumps("x"))
"""

_HOT_SAMPLER_CLEAN = """
    import sys
    import time


    class Ring:
        def add_sample(self, key):
            with self._fold_lock:
                fmap = self._open.folded
                n = fmap.get(key)
                fmap[key] = 1 if n is None else n + 1


    class Sampler:
        def sample_once(self):
            t0 = time.monotonic()
            add = self.ring.add_sample
            for tid, frame in sys._current_frames().items():
                sub = self._tids.get(tid)
                if sub is None:
                    sub = self._classify_coldpath(tid)
                add(sub + ";" + str(frame.f_lineno))
            self._adapt_coldpath(t0)

        def _classify_coldpath(self, tid):
            # a cold function MAY take its own lock and touch metrics
            with self._reg_lock:
                self._tids[tid] = "other"
            return "other"

        def _adapt_coldpath(self, t0):
            self.registry.gauge("corro.profile.overhead.pct").set(0.0)
"""

_PS_SCOPE = ("pkg/sampler.py",)


def test_profiler_safety_fires_on_seeded_violations(tmp_path):
    _write(tmp_path, "pkg/sampler.py", _HOT_SAMPLER_SLOPPY)
    ctx = AnalysisContext(str(tmp_path))
    fs = ProfilerSafetyChecker(scope=_PS_SCOPE).run(ctx)
    # sample_once: asyncio call, .acquire on _gate, f-string,
    # comprehension, sorted, json, logging, registry call, .agent
    # traversal; add_sample (reached THROUGH the `add = …` alias):
    # non-sanctioned with-lock.  _flush_coldpath's sins are exempt.
    assert len(fs) == 10, "\n".join(f.render() for f in fs)
    msgs = "\n".join(f.message for f in fs)
    assert "asyncio API" in msgs
    assert "acquires `_gate`" in msgs
    assert "acquires `_map_lock`" in msgs  # proves the alias edge
    assert "f-string" in msgs
    assert "comprehension" in msgs
    assert "sorted()" in msgs
    assert "json call" in msgs
    assert "logging" in msgs
    assert "registry call" in msgs
    assert "traverses `.agent`" in msgs
    assert "_flush_coldpath" not in msgs


def test_profiler_safety_minimal_fix_passes(tmp_path):
    _write(tmp_path, "pkg/sampler.py", _HOT_SAMPLER_CLEAN)
    ctx = AnalysisContext(str(tmp_path))
    fs = ProfilerSafetyChecker(scope=_PS_SCOPE).run(ctx)
    assert fs == [], "\n".join(f.render() for f in fs)


def test_profiler_safety_scope_is_explicit_files(tmp_path):
    # the rule scans the two named profiler files, nothing else — a
    # sloppy sampler elsewhere in the tree is some other rule's problem
    _write(tmp_path, "pkg/other.py", _HOT_SAMPLER_SLOPPY)
    ctx = AnalysisContext(str(tmp_path))
    assert ProfilerSafetyChecker(scope=_PS_SCOPE).run(ctx) == []


def test_profiler_safety_noqa_suppresses(tmp_path):
    body = _HOT_SAMPLER_SLOPPY.replace(
        'METRICS.counter("x").inc()',
        'METRICS.counter("x").inc()  # corro: noqa[profiler-safety]',
    )
    _write(tmp_path, "pkg/sampler.py", body)
    ctx = AnalysisContext(str(tmp_path))
    result = run_analysis(
        ctx, [ProfilerSafetyChecker(scope=_PS_SCOPE)], baseline={}
    )
    assert len(result.suppressed) == 1
    assert len(result.new) == 9


def test_profiler_safety_real_tree_is_clean():
    """The shipped sampler holds its own contract: everything reachable
    from `sample_once` is lock-free (but `_fold_lock`), asyncio-free
    and allocation-free, with all cold work behind `_coldpath` names —
    this pin keeps the hot path honest as the profiler grows."""
    fs = ProfilerSafetyChecker().run(AnalysisContext(REPO))
    assert fs == [], "\n".join(f.render() for f in fs)


def test_profiler_safety_reaches_the_fold_map(tmp_path):
    # the reachable set must actually cross the alias into profstore's
    # add_sample — an empty reachable set would vacuously "pass"
    _write(tmp_path, "pkg/sampler.py", _HOT_SAMPLER_SLOPPY)
    ctx = AnalysisContext(str(tmp_path))
    fs = ProfilerSafetyChecker(scope=_PS_SCOPE).run(ctx)
    assert any(f.symbol == "Ring.add_sample" for f in fs)


# -- 11. the metrics fold + baseline machinery ------------------------------


def test_metrics_fold_reports_same_inventory():
    """The lint_metrics fold is lossless: same 254 literal series (218
    at r19 + the 15 r20 alerting-plane series — corro.tsdb.*,
    corro.alerts.*, corro.metrics.{series,cardinality.dropped.total},
    corro.store.write.errors.total — + the 3 r21 write-path series:
    corro.write.finalize.columnar.total and the two
    corro.write.group.amortized.{flush,txs}.total, + the 6 r22
    remediation-plane series: corro.remediation.{actions.total,
    skips.total, reverts.total, armed},
    corro.sync.targeted.rounds.total and
    corro.digest.degraded.total — the oversize-digest degrade the A/B
    harness forced, + the 8 r23 profiling-plane series:
    corro.profile.{samples.total, shed.total, captures.total,
    overhead.pct}, corro.store.stmt.seconds,
    corro.write.profile.seconds and the two commit-flush series
    corro.store.commit.{flush.seconds, stall.total}, + the 4 r24
    committer/native-finalize series:
    corro.write.committer.{queue.depth, handoff.seconds} and
    corro.write.finalize.native.{total, unavailable}), same 2 wildcard
    sites, both
    directions clean, via BOTH the framework checker and the
    back-compat shim."""
    import lint_metrics

    assert MetricsDocChecker().run(AnalysisContext(REPO)) == []
    assert lint_metrics.lint() == []
    literals, wildcards = lint_metrics.scan_call_sites()
    assert len(literals) == 254
    assert len(wildcards) == 2
    names = lint_metrics.parse_components_table()
    assert len(names) == len(set(names))
    assert set(literals) <= set(names)


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    _write(tmp_path, "pkg/store.py", _RACY_CLASS)
    ctx = AnalysisContext(str(tmp_path))
    checker = LockDisciplineChecker(scope=("pkg",))
    finding = checker.run(ctx)[0]

    # grandfathered: the exact key is baselined -> not a new finding
    result = run_analysis(
        ctx, [checker], baseline={finding.key: "proven benign in test"}
    )
    assert result.new == [] and result.ok
    assert [w for _, w in result.baselined] == ["proven benign in test"]

    # stale: the violation is fixed but the baseline entry remains ->
    # the run fails so the grandfather list can only shrink on purpose
    _write(tmp_path, "pkg/store.py", _LOCKED_CLASS)
    ctx2 = AnalysisContext(str(tmp_path))
    result2 = run_analysis(
        ctx2, [checker], baseline={finding.key: "proven benign in test"}
    )
    assert result2.new == [] and not result2.ok
    assert result2.stale_keys == [finding.key]


def test_baseline_keys_are_line_number_free(tmp_path):
    # adding code ABOVE the finding must not churn the baseline key
    _write(tmp_path, "pkg/store.py", _RACY_CLASS)
    k1 = (
        LockDisciplineChecker(scope=("pkg",))
        .run(AnalysisContext(str(tmp_path)))[0]
        .key
    )
    _write(tmp_path, "pkg/store.py", "X = 1\nY = 2\n" + textwrap.dedent(_RACY_CLASS))
    k2 = (
        LockDisciplineChecker(scope=("pkg",))
        .run(AnalysisContext(str(tmp_path)))[0]
        .key
    )
    assert k1 == k2

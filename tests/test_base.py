"""HLC, Timestamp, Actor identity."""

import time

from corrosion_tpu.types.actor import Actor, ActorId, ClusterId
from corrosion_tpu.types.base import HLClock, Timestamp


def test_timestamp_roundtrip():
    ts = Timestamp.from_unix(1700000000.5)
    assert ts.secs == 1700000000
    assert abs(ts.to_unix() - 1700000000.5) < 1e-6
    assert not ts.is_zero()
    assert Timestamp.zero().is_zero()


def test_timestamp_ordering():
    a = Timestamp.from_unix(100.0)
    b = Timestamp.from_unix(100.5)
    assert a < b


def test_hlc_monotonic():
    clk = HLClock()
    prev = clk.new_timestamp()
    for _ in range(100):
        cur = clk.new_timestamp()
        assert cur.ntp64 > prev.ntp64
        prev = cur


def test_hlc_update_with_peer():
    clk = HLClock(max_delta_ms=300)
    peer = Timestamp.from_unix(time.time() + 0.1)
    assert clk.update_with_timestamp(peer)
    assert clk.new_timestamp().ntp64 > peer.ntp64
    # too far in the future → rejected
    far = Timestamp.from_unix(time.time() + 10.0)
    assert not clk.update_with_timestamp(far)


def test_actor_renew_and_conflict():
    a = Actor(id=ActorId.new_random(), addr="127.0.0.1:1234", ts=Timestamp.now())
    time.sleep(0.01)
    renewed = a.renew()
    assert renewed.bump == a.bump + 1
    assert renewed.wins_addr_conflict(a)
    assert renewed.id == a.id


def test_actor_id():
    aid = ActorId.new_random()
    assert ActorId.from_uuid_str(str(aid)) == aid
    assert len(aid.short()) == 8
    assert ClusterId(65535).value == 65535

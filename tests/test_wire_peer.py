"""Raw-socket fake peer against a live agent — wire-level parity proof.

The reference tests broadcast ordering with a raw quinn endpoint acting
as a fake peer (`broadcast/mod.rs:1104-1199`): bytes assembled outside
the agent stack, pushed at a real gossip listener, asserted to land in
SQLite. Mirrored here: a plain TCP socket (no framework client code on
the sending side beyond the byte codec itself) opens the uni lane to a
real agent's gossip port and pushes a speedy-layout BroadcastV1::Change;
the row must appear in the agent's database via the full ingestion path
(handle_changes → bookkeeping → CRDT apply), and the foreign actor must
be booked.
"""

import asyncio
import struct

from corrosion_tpu.devcluster import DevCluster, Topology
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.codec import (
    ChangesetFull,
    ChangeV1,
    ClusterId,
    encode_uni_payload,
)
from corrosion_tpu.types.pack import pack_columns

from tests.test_agent import TEST_SCHEMA, wait_until

FOREIGN = b"\x5a" * 16  # an actor the agent has never heard of


def _wire_change(version: int, row_id: int, text: str) -> bytes:
    cv = ChangeV1(
        actor_id=ActorId(FOREIGN),
        changeset=ChangesetFull(
            version=version,
            changes=(
                Change(
                    table="tests",
                    pk=pack_columns([row_id]),
                    cid="text",
                    val=text,
                    col_version=1,
                    db_version=version,
                    seq=0,
                    site_id=FOREIGN,
                    cl=1,
                    ts=Timestamp(42),
                ),
            ),
            seqs=(0, 0),
            last_seq=0,
            ts=Timestamp(42),
        ),
    )
    return encode_uni_payload(cv, ClusterId(0))


def test_raw_socket_peer_change_lands_in_sqlite():
    async def main():
        cluster = DevCluster(Topology.parse("a -> a"), schema_sql=TEST_SCHEMA)
        # single node: "a -> a" gives node a with no foreign bootstrap
        await cluster.start()
        agent = cluster.agents["a"]
        try:
            host, port = agent.actor.addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            payload = _wire_change(1, 7, "from-the-wire")
            # uni lane: lane byte then u32-BE length-delimited frame
            writer.write(b"U" + struct.pack(">I", len(payload)) + payload)
            await writer.drain()

            def row_present() -> bool:
                with agent.store.pooled_read() as conn:
                    rows = conn.execute(
                        "SELECT text FROM tests WHERE id = 7"
                    ).fetchall()
                return bool(rows) and rows[0][0] == "from-the-wire"

            assert await wait_until(row_present, timeout=15.0)
            # the foreign actor is booked with its version applied
            booked = agent.bookie.ensure(ActorId(FOREIGN))
            with booked.read() as bv:
                assert bv.contains_version(1)
            writer.close()
        finally:
            await cluster.stop()

    asyncio.run(main())

"""The r14 local-commit group coalescer (agent/run.py GroupCommitter).

Concurrent `make_broadcastable_changes` callers share one sqlite
BEGIN IMMEDIATE..COMMIT: consecutive db_versions inside one transaction,
one bookkeeping round for the group, per-writer SAVEPOINT rollback
isolation, and an unchanged solo fast path (a lone writer's batch is
size 1 and commits immediately).
"""

from __future__ import annotations

import asyncio

import sqlite3

from corrosion_tpu.agent.run import make_broadcastable_changes, shutdown
from corrosion_tpu.net.mem import MemNetwork

from tests.test_agent import boot, wait_until


def _insert(i: int, text: str = "t"):
    def fn(tx):
        return [
            tx.execute(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (i, text)
            )
        ]

    return fn


class _BeginCounter:
    """Count transaction starts on the write connection via the sqlite
    trace callback (BEGIN for solo/leader txs — savepoints don't BEGIN)."""

    def __init__(self, store):
        self.store = store
        self.begins = 0
        self.savepoints = 0

    def __enter__(self):
        def cb(stmt: str):
            head = stmt.lstrip().upper()
            if head.startswith("BEGIN"):
                self.begins += 1
            elif head.startswith("SAVEPOINT"):
                self.savepoints += 1

        self.store._conn.set_trace_callback(cb)
        return self

    def __exit__(self, *exc):
        self.store._conn.set_trace_callback(None)
        return False


def test_concurrent_writers_coalesce_into_fewer_commits():
    async def main():
        net = MemNetwork(seed=41)
        a = await boot(net, "agent-gc")
        n = 24
        try:
            with _BeginCounter(a.store) as counter:
                results = await asyncio.gather(
                    *(make_broadcastable_changes(a, _insert(i))
                      for i in range(n))
                )
            # every writer committed, with its own result + version
            versions = sorted(r.version for r in results)
            assert all(r.rows_affected == 1 for r in results)
            # consecutive db_versions with no gaps
            assert versions == list(range(versions[0], versions[0] + n))
            # the whole burst shared a handful of transactions — not one
            # BEGIN per writer (each writer still gets its own SAVEPOINT)
            assert counter.begins < n / 2, (
                f"{counter.begins} BEGINs for {n} writers"
            )
            assert counter.savepoints >= n - counter.begins
            rows = a.store._conn.execute(
                "SELECT count(*) AS n FROM tests"
            ).fetchone()["n"]
            assert rows == n
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_failed_writer_rolls_back_alone():
    async def main():
        net = MemNetwork(seed=43)
        a = await boot(net, "agent-gc-iso")
        try:
            def bad(tx):
                tx.execute("INSERT INTO tests (id, text) VALUES (1, 'pre')")
                tx.execute("INSERT INTO nope VALUES (1)")  # no such table
                return []

            good_futs = [
                make_broadcastable_changes(a, _insert(i + 10))
                for i in range(4)
            ]
            bad_fut = make_broadcastable_changes(a, bad)
            results = await asyncio.gather(
                *good_futs, bad_fut, return_exceptions=True
            )
            errors = [r for r in results if isinstance(r, BaseException)]
            assert len(errors) == 1
            assert isinstance(errors[0], sqlite3.Error)
            # only the failed writer rolled back: its partial INSERT is
            # gone, all four good writers' rows are durable
            ids = [
                r["id"]
                for r in a.store._conn.execute(
                    "SELECT id FROM tests ORDER BY id"
                )
            ]
            assert ids == [10, 11, 12, 13]
            # and the survivors' versions are gapless (the failed sub-tx
            # consumed no db_version)
            versions = sorted(
                r.version for r in results
                if not isinstance(r, BaseException)
            )
            assert versions == list(
                range(versions[0], versions[0] + 4)
            )
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_solo_writer_fast_path_one_commit():
    """A lone writer must not wait for company: exactly one BEGIN, and
    the changes broadcast/apply end to end."""

    async def main():
        net = MemNetwork(seed=47)
        a = await boot(net, "agent-gc-solo")
        try:
            with _BeginCounter(a.store) as counter:
                res = await make_broadcastable_changes(a, _insert(1, "solo"))
            assert res.version == 1
            assert counter.begins == 1
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_group_commit_disabled_falls_back_to_solo_path():
    async def main():
        net = MemNetwork(seed=53)
        a = await boot(net, "agent-gc-off")
        a.config.perf.group_commit = False
        try:
            with _BeginCounter(a.store) as counter:
                results = await asyncio.gather(
                    *(make_broadcastable_changes(a, _insert(i))
                      for i in range(6))
                )
            assert sorted(r.version for r in results) == list(range(1, 7))
            assert counter.begins == 6  # one tx per writer, no savepoints
            assert counter.savepoints == 0
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_grouped_writes_replicate_to_peer():
    """Changes committed through a shared transaction still broadcast
    per writer and converge on a gossiping peer."""

    async def main():
        net = MemNetwork(seed=59)
        a = await boot(net, "agent-gc-a")
        b = await boot(net, "agent-gc-b", bootstrap=["agent-gc-a"])
        try:
            await wait_until(lambda: len(a.members) >= 1, timeout=10)
            await asyncio.gather(
                *(make_broadcastable_changes(a, _insert(i))
                  for i in range(8))
            )

            def applied():
                row = b.store._conn.execute(
                    "SELECT count(*) AS n FROM tests"
                ).fetchone()
                return row["n"] == 8

            assert await wait_until(applied, timeout=20)
        finally:
            await shutdown(b)
            await shutdown(a)

    asyncio.run(main())


def test_group_finalize_equivalent_to_sequential_commits():
    """The store-level pin for the batched finalize: N sub-transactions
    finalized through ONE `finalize_group` pass produce byte/clock-
    identical changes, db_versions and table state vs the same
    transactions committed sequentially (each its own solo tx) — across
    cross-writer interactions: same-pk updates, delete then re-create
    by a LATER writer, col_version continuation."""
    import random

    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.actor import ActorId
    from corrosion_tpu.types.base import Timestamp

    from tests.test_finalize_batch import SCHEMA, dump_state

    rng = random.Random(77)
    site = ActorId(bytes([5]) * 16)

    def random_tx_ops():
        ops = []
        for _ in range(rng.randint(1, 4)):
            kv_id = rng.randint(1, 4)
            roll = rng.random()
            if roll < 0.45:
                ops.append((
                    "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
                    (kv_id, rng.choice(["x", "y"]), rng.randint(0, 9)),
                ))
            elif roll < 0.75:
                ops.append((
                    "UPDATE kv SET b = b + 1 WHERE id = ?", (kv_id,)
                ))
            else:
                ops.append(("DELETE FROM kv WHERE id = ?", (kv_id,)))
        return ops

    batches = [
        [random_tx_ops() for _ in range(rng.randint(2, 6))]
        for _ in range(8)
    ]

    def run_sequential():
        st = CrdtStore(":memory:", site_id=site)
        st.apply_schema_sql(SCHEMA)
        all_changes = []
        n = 0
        for batch in batches:
            for ops in batch:
                n += 1
                with st.write_tx(Timestamp.from_unix(n)) as tx:
                    for sql, params in ops:
                        tx.execute(sql, params)
                    changes, _v, _ls = tx.commit()
                all_changes.append([tuple(c.__dict__.values()) for c in []])
                all_changes[-1] = [
                    (c.table, c.pk, c.cid, c.val, c.col_version,
                     c.db_version, c.seq, c.cl) for c in changes
                ]
        return all_changes, dump_state(st)

    def run_grouped():
        st = CrdtStore(":memory:", site_id=site)
        st.apply_schema_sql(SCHEMA)
        all_changes = []
        n = 0
        for batch in batches:
            group = []
            with st.group_tx():
                for ops in batch:
                    n += 1
                    with st.write_tx(
                        Timestamp.from_unix(n), nested=True
                    ) as tx:
                        for sql, params in ops:
                            tx.execute(sql, params)
                        group.append((tx.commit_deferred(), tx.ts))
                finalized = st.finalize_group(group)
            for changes, _dv, _ls in finalized:
                all_changes.append([
                    (c.table, c.pk, c.cid, c.val, c.col_version,
                     c.db_version, c.seq, c.cl) for c in changes
                ])
        return all_changes, dump_state(st)

    seq_changes, seq_dump = run_sequential()
    grp_changes, grp_dump = run_grouped()
    assert grp_changes == seq_changes
    assert grp_dump == seq_dump

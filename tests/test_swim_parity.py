"""Parity between the three SWIM execution paths.

The framework runs the same protocol three ways:
  1. event-driven per-node state machines over sockets
     (`corrosion_tpu.agent.membership`, the foca-equivalent used by real
     agents — `klukai-agent/src/broadcast/mod.rs:121-386`),
  2. the batched array kernel (`corrosion_tpu.ops.swim`, one jitted tick
     for all members), and
  3. the member-sharded kernel over a device mesh
     (`corrosion_tpu.parallel`, the multi-chip path).

These tests pin the equivalences the design claims (BASELINE.md north
star #2): 3↔2 must be *bit-identical* (same deterministic integer
computation, different layout), and 1↔2 must agree behaviorally —
convergence within the same number of protocol periods (to a tolerance),
failure detection inside the same suspicion window, and no false
positives in a healthy cluster.
"""

import asyncio
import math
import random
from typing import NamedTuple

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.agent.membership import Membership, SwimConfig
from corrosion_tpu.net.mem import LinkFaults, MemNetwork
from corrosion_tpu.ops import swim
from corrosion_tpu.parallel import member_mesh, shard_swim_state, sharded_tick
from corrosion_tpu.runtime.tripwire import Tripwire
from corrosion_tpu.types.actor import Actor, ActorId
from corrosion_tpu.types.base import Timestamp

# ---------------------------------------------------------------------------
# sharded ↔ unsharded: exact equivalence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ticks", [1, 4])
@pytest.mark.parametrize("gossip_mode", ["pick", "shift"])
def test_sharded_tick_matches_unsharded(ticks, gossip_mode):
    """The sharded kernel is the SAME integer computation with layout
    constraints, so its output must be bit-identical to the single-device
    kernel under the same rng sequence — in both gossip modes (shift's
    offset row-gather crosses shard boundaries via XLA collectives)."""
    n_dev = 8
    devices = jax.devices()
    assert len(devices) >= n_dev, "conftest forces an 8-device CPU mesh"
    params = swim.SwimParams(n=8 * n_dev, gossip_mode=gossip_mode)

    state_a = swim.init_state(params, jax.random.PRNGKey(3))
    mesh = member_mesh(devices[:n_dev])
    state_b = shard_swim_state(
        swim.init_state(params, jax.random.PRNGKey(3)), mesh
    )
    stick = sharded_tick(params, mesh)

    rng = jax.random.PRNGKey(9)
    for _ in range(ticks):
        rng, key = jax.random.split(rng)
        state_a = swim.tick(state_a, key, params)
        state_b = stick(state_b, key)

    for name, arr_a in state_a._asdict().items():
        arr_b = getattr(state_b, name)
        assert jnp.array_equal(arr_a, arr_b), f"field {name} diverged"


def test_sharded_stats_match_unsharded():
    """membership_stats must not depend on the layout either."""
    n_dev = 8
    params = swim.SwimParams(n=8 * n_dev)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    for _ in range(3):
        rng, key = jax.random.split(rng)
        state = swim.tick(state, key, params)

    mesh = member_mesh(jax.devices()[:n_dev])
    sharded = shard_swim_state(state, mesh)
    a = swim.membership_stats(state)
    b = swim.membership_stats(sharded)
    for k in a:
        assert a[k] == pytest.approx(b[k], abs=1e-9)


# ---------------------------------------------------------------------------
# batched ↔ event-driven: behavioral parity
# ---------------------------------------------------------------------------

N_PARITY = 8
# Shared protocol geometry: a suspicion window of ~4 protocol periods in
# both paths, so detection-latency comparisons are apples-to-apples.
SUSPICION_PERIODS = 4
EV_PERIOD = 0.05
EV_CFG = SwimConfig(
    probe_period=EV_PERIOD,
    probe_rtt=0.02,
    # suspect_timeout(n) = mult * log2(n+2) * period  ==  4 periods
    suspicion_mult=SUSPICION_PERIODS / math.log2(N_PARITY + 2),
)
SIM_PARAMS = dict(suspicion_ticks=SUSPICION_PERIODS, seeds_per_member=1)
# generous shared budget: both paths must converge an 8-member boot
# within this many protocol periods
CONVERGE_PERIODS = 30
DETECT_PERIODS = SUSPICION_PERIODS + 8  # probe + suspicion + gossip slack


def _sim_cluster(n=N_PARITY, seed=0):
    from corrosion_tpu.models.cluster import ClusterSim

    return ClusterSim(
        n,
        seed=seed,
        seeds_per_member=SIM_PARAMS["seeds_per_member"],
        seed_mode="hub",
        suspicion_ticks=SIM_PARAMS["suspicion_ticks"],
    )


def _mk_node(net: MemNetwork, i: int):
    addr = f"node{i}"
    actor = Actor(
        id=ActorId(bytes([i]) * 16), addr=addr, ts=Timestamp.from_unix(i)
    )
    ms = Membership(actor, net.transport(addr), EV_CFG, rng=random.Random(i))

    async def on_uni(src, data):
        pass

    async def on_bi(stream):
        stream.close()

    net.listener(addr).serve(ms.handle_datagram, on_uni, on_bi)
    return ms


async def _ev_boot(net):
    tw = Tripwire()
    nodes = [_mk_node(net, i + 1) for i in range(N_PARITY)]
    for ms in nodes:
        ms.start(tw)
    # hub join: everyone announces to node1 (sim analog: seed_mode="hub")
    for ms in nodes[1:]:
        await ms.announce("node1")
    return tw, nodes


class _EvElapsed(NamedTuple):
    raw: float  # wall-clock periods
    eff: float  # wall minus observed scheduler starvation, in periods


async def _ev_periods_until(pred, max_periods, step=EV_PERIOD / 2):
    """Periods until pred(), or None past the budget.

    On a loaded 1-core box asyncio timers fire late and wall-clock
    period counts flap (r4 Weak #6/#8 class). `eff` subtracts the
    starvation this monitor itself observes on its own sleeps (the
    loopmon lag trick) — use it for upper bounds and cross-path
    agreement. `raw` keeps the wall measurement for lower bounds the
    product guarantees in wall time (the suspicion window). The budget
    is spent in effective time, so the protocol keeps its full allowance
    under load instead of timing out on starvation."""
    loop = asyncio.get_event_loop()
    start = loop.time()
    lag = 0.0
    while True:
        now = loop.time()
        if pred():
            elapsed = now - start
            return _EvElapsed(
                elapsed / EV_PERIOD,
                max(0.0, (elapsed - lag) / EV_PERIOD),
            )
        if now - start - lag >= max_periods * EV_PERIOD:
            return None
        t0 = loop.time()
        await asyncio.sleep(step)
        lag += max(0.0, loop.time() - t0 - step)


def _sim_periods_until(sim, pred, max_periods):
    for tick in range(1, max_periods + 1):
        sim.step()
        if pred(sim.stats()):
            return tick
    return None


def test_parity_bootstrap_convergence():
    """Both paths bring an N-member hub-boot to full mutual knowledge
    within the shared period budget, with zero false positives."""
    sim = _sim_cluster()
    sim_t = _sim_periods_until(
        sim, lambda s: s["coverage"] >= 1.0, CONVERGE_PERIODS
    )
    assert sim_t is not None, "batched kernel failed to converge"
    assert sim.stats()["false_positive"] == 0.0

    async def main():
        net = MemNetwork(seed=11)
        tw, nodes = await _ev_boot(net)
        ev_t = await _ev_periods_until(
            lambda: all(ms.cluster_size == N_PARITY for ms in nodes),
            CONVERGE_PERIODS,
        )
        assert ev_t is not None, "event-driven path failed to converge"
        for ms in nodes:
            await ms.stop()
        return ev_t.eff

    ev_t = asyncio.run(main())
    # both land inside the shared budget AND within 2x of each other
    # (measured: sim 3 vs ev ~2.7 periods — the paths share the same
    # protocol cadence, so a real regression shows up well before 2x)
    assert sim_t <= CONVERGE_PERIODS and ev_t <= CONVERGE_PERIODS
    assert max(sim_t, ev_t) / max(1.0, min(sim_t, ev_t)) <= 2.0, (
        sim_t,
        ev_t,
    )


def test_parity_failure_detection_window():
    """A crashed member is declared down by every live peer within the
    suspicion window (+ slack) in both paths."""
    sim = _sim_cluster()
    assert (
        _sim_periods_until(
            sim, lambda s: s["coverage"] >= 1.0, CONVERGE_PERIODS
        )
        is not None
    )
    sim.crash(N_PARITY - 1)
    sim_det = _sim_periods_until(
        sim, lambda s: s["detected"] >= 1.0, DETECT_PERIODS * 3
    )
    assert sim_det is not None, "batched kernel never detected the crash"

    async def main():
        net = MemNetwork(seed=13)
        tw, nodes = await _ev_boot(net)
        assert await _ev_periods_until(
            lambda: all(ms.cluster_size == N_PARITY for ms in nodes),
            CONVERGE_PERIODS,
        )
        await nodes[-1].stop()
        net.take_down(f"node{N_PARITY}")
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        drops = {}

        def pred():
            for i, ms in enumerate(nodes[:-1]):
                if i not in drops and ms.cluster_size == N_PARITY - 1:
                    drops[i] = (loop.time() - t0) / EV_PERIOD
            return len(drops) == N_PARITY - 1

        ev_all = await _ev_periods_until(pred, DETECT_PERIODS * 3)
        assert ev_all is not None, "event-driven path never detected"
        for ms in nodes[:-1]:
            await ms.stop()
        # per-node stamps are raw wall periods; rescale the median by
        # the run's observed starvation ratio so its upper bound is in
        # compensated time like ev_all.eff (lag accrues roughly
        # uniformly across the window)
        med_raw = sorted(drops.values())[len(drops) // 2]
        return ev_all, med_raw * (ev_all.eff / max(ev_all.raw, 1e-9))

    ev_all, ev_med = asyncio.run(main())
    # The suspicion-window arithmetic both paths share applies to the
    # MEDIAN node: detection can only complete after the suspicion
    # window elapses (probe + window) and lands inside window + gossip
    # slack; the paths agree within one suspicion window (measured: sim
    # 10 vs ev ~7.5 median). The ALL-nodes time gets one extra
    # suspicion window: SWIM dissemination is probabilistic, and a
    # straggler that misses the piggybacked DOWN legitimately pays (a
    # slice of) its own probe + suspicion window — measured tail 10-12
    # periods over 20 trials (the event path's sim has no such tail:
    # the batched kernel disseminates in lockstep). Lower bound on raw
    # wall periods (the suspicion window is a wall-clock guarantee,
    # load only lengthens it); upper bounds on starvation-compensated
    # periods (_EvElapsed.eff).
    assert SUSPICION_PERIODS <= sim_det <= DETECT_PERIODS, sim_det
    assert SUSPICION_PERIODS <= ev_all.raw, ev_all
    assert ev_med <= DETECT_PERIODS, (ev_med, ev_all)
    assert ev_all.eff <= DETECT_PERIODS + SUSPICION_PERIODS, ev_all
    # window-grid comparison (r21): sim_det is an integer period count
    # and ev_med a starvation-rescaled float, so both measurements only
    # resolve whole suspicion periods — the r15 half-period margin still
    # tripped when load pushed the float to 4.005 against the exact
    # 4.5-period bound.  Quantizing the gap to the integer period grid
    # (floor, with an epsilon so an exact integer gap stays itself)
    # pins the assert to "within SUSPICION_PERIODS whole windows": a
    # fractional measurement can never land exactly on the bound again,
    # and a real dissemination change (one full extra period) still
    # fails
    assert (
        math.floor(abs(sim_det - ev_med) + 1e-9) <= SUSPICION_PERIODS
    ), (sim_det, ev_med)


def test_parity_no_false_positives_under_loss():
    """With mild iid datagram loss, neither path falsely downs a live
    member over an extended healthy window (refutation works)."""
    from corrosion_tpu.models.cluster import ClusterSim

    sim = ClusterSim(
        N_PARITY,
        seed=5,
        seeds_per_member=1,
        seed_mode="hub",
        suspicion_ticks=SIM_PARAMS["suspicion_ticks"],
        loss=0.05,
    )
    for _ in range(CONVERGE_PERIODS * 2):
        sim.step()
    assert sim.stats()["false_positive"] == 0.0

    async def main():
        net = MemNetwork(seed=17, faults=LinkFaults(datagram_loss=0.05))
        tw, nodes = await _ev_boot(net)
        assert await _ev_periods_until(
            lambda: all(ms.cluster_size == N_PARITY for ms in nodes),
            CONVERGE_PERIODS * 2,
        )
        # healthy window: nobody may get kicked
        await asyncio.sleep(CONVERGE_PERIODS * EV_PERIOD)
        sizes = [ms.cluster_size for ms in nodes]
        for ms in nodes:
            await ms.stop()
        assert all(s == N_PARITY for s in sizes), sizes

    asyncio.run(main())


def test_multihost_mesh_matches_flat_mesh():
    """The [hosts, members] mesh (DCN layout: host axis outermost, each
    host's member block ICI-contiguous) is a LAYOUT change only — the
    sharded tick must stay bit-identical to the flat member mesh. In a
    single-process job multihost_member_mesh folds all 8 virtual devices
    into hosts=1, which is the degenerate case CI can drive."""
    from corrosion_tpu.parallel import (
        multihost_member_mesh,
        shard_member_state,
    )

    n_dev = 8
    devices = jax.devices()
    assert len(devices) >= n_dev
    params = swim.SwimParams(n=8 * n_dev)

    flat = member_mesh(devices[:n_dev])
    multi = multihost_member_mesh()
    assert multi.devices.shape == (1, len(devices))

    state_a = shard_member_state(
        swim.init_state(params, jax.random.PRNGKey(3)), flat
    )
    state_b = shard_member_state(
        swim.init_state(params, jax.random.PRNGKey(3)), multi
    )
    tick_flat = sharded_tick(params, flat)
    tick_multi = sharded_tick(params, multi)

    rng = jax.random.PRNGKey(9)
    for _ in range(5):
        rng, key = jax.random.split(rng)
        state_a = tick_flat(state_a, key)
        state_b = tick_multi(state_b, key)

    for name, arr_a in state_a._asdict().items():
        arr_b = getattr(state_b, name)
        assert jnp.array_equal(arr_a, arr_b), f"field {name} diverged"

"""runtime/loopmon.py coverage (r20 satellite): the lag histogram
sampling, the REPORT_EVERY max-lag window semantics, and the feed into
the metrics TSDB the alerting plane rides on.
"""

from __future__ import annotations

import asyncio
import time

from corrosion_tpu.runtime.loopmon import loop_lag_monitor
from corrosion_tpu.runtime.metrics import Registry
from corrosion_tpu.runtime.tsdb import MetricsTSDB


def test_lag_histogram_samples_every_wakeup():
    reg = Registry()

    asyncio.run(loop_lag_monitor(
        interval=0.005, report_every=3, registry=reg, max_samples=7,
    ))
    h = reg.histogram("corro.runtime.loop.lag.seconds")
    assert h.count == 7  # one observation per monitor wakeup
    assert reg.counter("corro.runtime.loop.ticks").value == 7
    # a quiet loop's lag is near zero: everything in the low buckets
    assert h.total < 1.0


def test_report_every_window_tracks_then_resets_max_lag():
    """The max-lag gauge publishes the WORST lag of the last window and
    the window then resets — a one-off stall must not stick forever."""
    reg = Registry()

    async def main():
        async def stall_once():
            await asyncio.sleep(0.01)
            time.sleep(0.08)  # block the loop: real scheduling lag

        stall = asyncio.ensure_future(stall_once())
        await loop_lag_monitor(
            interval=0.005, report_every=4, registry=reg, max_samples=4,
        )
        first = reg.gauge("corro.runtime.loop.lag.max.seconds").value
        # second window: no stalls -> the gauge RESETS to a small value
        await loop_lag_monitor(
            interval=0.005, report_every=4, registry=reg, max_samples=4,
        )
        await stall
        return first

    first = asyncio.run(main())
    assert first >= 0.05  # the blocked wakeup was observed
    second = reg.gauge("corro.runtime.loop.lag.max.seconds").value
    assert second < first  # window max, not an all-time max
    # tasks-alive gauge published at each window boundary
    assert reg.gauge("corro.runtime.loop.tasks.alive").value >= 1


def test_partial_window_does_not_publish():
    """Samples short of REPORT_EVERY leave the gauge untouched — the
    window boundary is the publication point."""
    reg = Registry()
    asyncio.run(loop_lag_monitor(
        interval=0.005, report_every=10, registry=reg, max_samples=4,
    ))
    assert reg.gauge("corro.runtime.loop.lag.max.seconds").value == 0.0
    assert reg.histogram("corro.runtime.loop.lag.seconds").count == 4


def test_loopmon_feeds_the_tsdb():
    """The alerting substrate end to end: monitor publishes → TSDB
    sample captures the lag gauge and the tick counter's rate — the
    exact fields the loop-lag rule and the health score evaluate."""
    reg = Registry()
    db = MetricsTSDB(registry=reg, sample_interval_secs=0.01)

    async def main():
        await loop_lag_monitor(
            interval=0.005, report_every=2, registry=reg, max_samples=2,
        )
        db.sample_once()  # first sight of the tick counter
        await loop_lag_monitor(
            interval=0.005, report_every=2, registry=reg, max_samples=2,
        )
        db.sample_once()  # second: a real rate interval elapsed

    asyncio.run(main())
    assert db.aggregate(
        "corro.runtime.loop.lag.max.seconds", window_secs=60,
        across="max", over="last",
    ) is not None
    rate = db.aggregate(
        "corro.runtime.loop.ticks:rate", window_secs=60,
        across="sum", over="last",
    )
    assert rate is not None and rate > 0

"""Array-resident CRDT merge kernel vs the host engines.

The jitted decision kernel (`ops/crdt_merge.py`) must produce a database
state and impactful set identical to the pure-Python reference loop (the
semantic pin of `agent/util.rs:703-1310`) for ANY change sequence — the
same bar `native/crdt_batch.cpp` is held to in test_crdt_batch.py.
Batches the kernel cannot decide on-device (value ties at inexact
digests) must fall back without changing observable behavior.
"""

import random

import pytest

from corrosion_tpu.ops.crdt_merge import value_digest
from tests.test_crdt_batch import (
    apply_reference,
    dump_state,
    mk_store,
    random_changes,
    random_rich_changes,
)


def _cmp_digests(a, b):
    da, db = value_digest(a), value_digest(b)
    return (da[:4] > db[:4]) - (da[:4] < db[:4])


def test_value_digest_orders_like_cmp_values():
    from corrosion_tpu.types.values import cmp_values

    rng = random.Random(7)
    pool = [
        None, 0, 1, -1, 2**40, -(2**40), 0.5, -0.5, 1.0, 3.14,
        "", "a", "ab", "abc", "zz", "abc\x00", "abcdefghijklm",
        b"", b"\x00", b"\xff", b"abc", bytearray(b"zz"),
    ]
    for _ in range(2000):
        a, b = rng.choice(pool), rng.choice(pool)
        want = cmp_values(a, b)
        got = _cmp_digests(a, b)
        assert got == want, (a, b, got, want)


def test_value_digest_exactness_boundaries():
    # 13-byte text: exact; 14+: inexact
    assert value_digest("x" * 13)[4] is True
    assert value_digest("x" * 14)[4] is False
    # ints beyond float64-exact range: inexact
    assert value_digest(2**53)[4] is True
    assert value_digest(2**53 + 1)[4] is False
    # equal-prefix exact values order by length (prefix rule)
    assert _cmp_digests("abc", "abcd") == -1
    assert _cmp_digests("abc", "abc\x00") == -1
    # two long values with equal prefixes tie (inexact -> host decides)
    assert _cmp_digests("y" * 20, "y" * 30) == 0


def test_array_matches_python_randomized(monkeypatch):
    for seed in range(8):
        rng = random.Random(3000 + seed)
        changes = random_changes(rng, 120)

        monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
        a = mk_store()
        got_array = a.apply_changes(changes).impactful

        monkeypatch.setenv("CORRO_CRDT_ENGINE", "python")
        b = mk_store()
        got_python = b.apply_changes(changes).impactful

        assert got_array == got_python, f"seed {seed}"
        assert dump_state(a) == dump_state(b), f"seed {seed}"
        a.close()
        b.close()


def test_array_matches_python_rich_values(monkeypatch):
    """Value-type-rich batches incl. long strings that force the
    ambiguity fallback: observable behavior must not change."""
    for seed in range(6):
        rng = random.Random(4000 + seed)
        changes = random_rich_changes(rng, 150)

        monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
        a = mk_store()
        got_array = a.apply_changes(changes).impactful

        monkeypatch.setenv("CORRO_CRDT_ENGINE", "python")
        b = mk_store()
        got_python = b.apply_changes(changes).impactful

        assert got_array == got_python, f"seed {seed}"
        assert dump_state(a) == dump_state(b), f"seed {seed}"
        a.close()
        b.close()


def test_array_matches_per_row_split_batches(monkeypatch):
    monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
    rng = random.Random(5151)
    changes = random_changes(rng, 180)
    a, b = mk_store(), mk_store()
    for i in range(0, len(changes), 13):
        a.apply_changes(changes[i : i + 13])
    apply_reference(b, changes)
    assert dump_state(a) == dump_state(b)
    a.close()
    b.close()


def test_array_kernel_actually_decides(monkeypatch):
    """Guard against the kernel silently declining every batch (which
    would make the equivalence tests vacuous): on a digest-friendly
    batch the array engine must decide without fallback."""
    import corrosion_tpu.ops.crdt_merge as m

    calls = {"decided": 0, "declined": 0}
    real = m.merge_table_array

    def spy(*args, **kw):
        out = real(*args, **kw)
        calls["decided" if out is not None else "declined"] += 1
        return out

    monkeypatch.setattr(m, "merge_table_array", spy)
    monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
    rng = random.Random(99)
    changes = random_changes(rng, 100)
    st = mk_store()
    st.apply_changes(changes)
    st.close()
    assert calls["decided"] > 0, calls


def test_array_even_cl_with_non_sentinel_cid(monkeypatch):
    """Even-cl (delete) changes carrying a non-sentinel cid: the
    reference loop records only the sentinel clock entry and ignores the
    value — the kernel must not flush a clock/cell row for the cid (an
    input class the randomized generators never produce)."""
    import random as _r

    from corrosion_tpu.types.base import Timestamp
    from corrosion_tpu.types.change import SENTINEL, Change
    from corrosion_tpu.types.pack import pack_columns
    from tests.test_crdt_batch import SITES

    site = SITES[0].bytes16
    pk = pack_columns([1])

    def ch(cl, cid, val, cv, dbv):
        return Change(
            table="kv", pk=pk, cid=cid, val=val, col_version=cv,
            db_version=dbv, seq=0, site_id=site, cl=cl,
            ts=Timestamp.from_unix(dbv),
        )

    cases = [
        # lone even change with a cid
        [ch(2, "a", "ghost", 3, 1)],
        # even-with-cid then odd recreate
        [ch(2, "a", "ghost", 3, 1), ch(3, "b", 7, 1, 2)],
        # odd write, even-with-cid delete, odd recreate
        [ch(1, "a", "x", 1, 1), ch(2, "b", "ghost", 9, 2),
         ch(3, "a", "y", 1, 3)],
        # equal-cl even-with-cid against an even local (must lose)
        [ch(2, SENTINEL, None, 1, 1), ch(2, "a", "ghost", 5, 2)],
    ]
    for i, changes in enumerate(cases):
        monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
        a = mk_store()
        got_a = a.apply_changes(list(changes)).impactful
        monkeypatch.setenv("CORRO_CRDT_ENGINE", "python")
        b = mk_store()
        got_b = b.apply_changes(list(changes)).impactful
        assert got_a == got_b, f"case {i}"
        assert dump_state(a) == dump_state(b), f"case {i}"
        a.close()
        b.close()


def test_unknown_engine_rejected(monkeypatch):
    monkeypatch.setenv("CORRO_CRDT_ENGINE", "arry")
    st = mk_store()
    from tests.test_crdt_batch import random_changes as _rc

    with pytest.raises(ValueError, match="CORRO_CRDT_ENGINE"):
        st.apply_changes(_rc(random.Random(1), 5))
    st.close()

"""The r19 end-to-end tracing pin: one HTTP write on node A produces ONE
trace_id whose stage spans (write→broadcast→recv→apply→match→deliver)
cross two nodes, collected through the fake-OTLP collector
(tests/test_otel.py pattern) and served by GET /v1/traces with a
per-stage breakdown — and the tail sampler's verdicts are exercised
live and DETERMINISTICALLY: a healthy write (lottery disabled) is
dropped at trace close; a write breaching an SLO stage target is kept.
"""

from __future__ import annotations

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime import otel, tracestore

from tests.test_agent import wait_until
from tests.test_http_api import boot_with_api
from tests.test_pubsub_http import next_of

E2E_STAGES = ("write", "broadcast", "recv", "apply", "match", "deliver")


class _Collector(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length))
        self.server.bodies.append((self.path, body))
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def collector():
    srv = HTTPServer(("127.0.0.1", 0), _Collector)
    srv.bodies = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()
    otel.configure(None)
    tracestore.configure(None)


def _otlp_stage_spans(srv):
    """Stage-tagged spans only: the background sync plane's untagged
    spans (sync.client / sync.server) keep the r11 direct-export path
    and are not part of the tail-sampled verdict under test."""
    out = []
    for _path, body in srv.bodies:
        for rs in body["resourceSpans"]:
            for ss in rs["scopeSpans"]:
                for s in ss["spans"]:
                    attrs = {x["key"]: x["value"] for x in s["attributes"]}
                    if "stage" in attrs:
                        out.append(s)
    return out


def _force_close(st):
    """Advance past idle-close for everything buffered and sweep (the
    test drives sweeps by hand: auto_sweep=False)."""
    import time

    return st.sweep(now=time.monotonic() + st.idle_close_secs + 1)


async def _traced_write(st, a, client_a, it, rowid):
    """One HTTP write on A observed on B's subscription stream; returns
    the buffered trace id (the one new trace the write opened)."""
    before = set(st._buf)
    await client_a.execute(
        [["INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
          [rowid, f"t{rowid}"]]]
    )
    ev = await next_of(it, "change", timeout=15.0)
    assert ev["change"][2][0] == rowid

    new = [t for t in st._buf if t not in before]
    assert await wait_until(
        lambda: any(
            {r["attrs"]["stage"] for r in st._buf[t].spans} >= set(E2E_STAGES)
            for t in new
            if t in st._buf
        ),
        timeout=10.0,
    ), {t: [r["attrs"]["stage"] for r in st._buf[t].spans] for t in new}
    (tid,) = [
        t for t in new
        if {r["attrs"]["stage"] for r in st._buf[t].spans} >= set(E2E_STAGES)
    ]
    return tid


def test_one_write_one_trace_across_two_nodes_tail_sampled(collector):
    port = collector.server_address[1]

    async def main():
        net = MemNetwork(seed=67)
        a, api_a, client_a = await boot_with_api(net, "trace-a")
        b, api_b, client_b = await boot_with_api(net, "trace-b", ["trace-a"])
        otel.configure(f"http://127.0.0.1:{port}", flush_interval_s=60.0)
        # deterministic tail sampler: lottery OFF, targets unreachable —
        # the healthy write can only be dropped
        st = tracestore.configure(
            targets={s: 100.0 for s in E2E_STAGES},
            lottery_n=0,
            auto_sweep=False,
        )
        try:
            await wait_until(
                lambda: len(a.members) == 1 and len(b.members) == 1
            )
            stream = client_b.subscribe("SELECT id, text FROM tests")
            it = stream.__aiter__()
            await next_of(it, "eoq")

            # -- healthy write: buffered, then DROPPED at close ---------
            tid_healthy = await _traced_write(st, a, client_a, it, 41)
            spans = st._buf[tid_healthy].spans
            actors = {
                r["attrs"]["actor"] for r in spans if "actor" in r["attrs"]
            }
            assert actors == {str(a.actor_id), str(b.actor_id)}
            _force_close(st)
            assert st.kept() == []
            assert st.census()["dropped_total"] >= 1
            otel.exporter().flush()
            # dropped = its stage spans are never exported
            assert _otlp_stage_spans(collector) == []

            # -- breaching write: the apply stage target is 0 → KEPT ----
            st.targets["apply"] = 0.0
            tid_slow = await _traced_write(st, a, client_a, it, 42)
            _force_close(st)
            kept = st.kept(n=5)
            assert [t["trace_id"] for t in kept] == [tid_slow]
            rec = kept[0]
            assert rec["reason"] == "slo:apply"
            assert rec["n_spans"] >= 5
            assert set(rec["stages"]) >= set(E2E_STAGES)
            assert rec["actors"] == sorted(
                [str(a.actor_id), str(b.actor_id)]
            )
            assert rec["tables"] == ["tests"]

            # kept spans were forwarded to the OTLP collector: ONE trace
            # id, ≥5 stage spans, both nodes represented
            otel.exporter().flush()
            exported = _otlp_stage_spans(collector)
            assert {s["traceId"] for s in exported} == {tid_slow}
            by_stage = {}
            for s in exported:
                attrs = {x["key"]: x["value"] for x in s["attributes"]}
                by_stage.setdefault(
                    attrs["stage"]["stringValue"], []
                ).append(attrs)
            assert set(by_stage) >= set(E2E_STAGES)
            exported_actors = {
                a_["actor"]["stringValue"]
                for group in by_stage.values()
                for a_ in group
                if "actor" in a_
            }
            assert exported_actors == {str(a.actor_id), str(b.actor_id)}

            # -- the HTTP planes serve the same verdicts ----------------
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{api_b.addrs[0]}/v1/traces", params={"n": "5"}
                ) as resp:
                    assert resp.status == 200
                    body = await resp.json()
                async with s.get(
                    f"http://{api_b.addrs[0]}/v1/slo"
                ) as resp:
                    slo_body = await resp.json()
                async with s.get(
                    f"http://{api_b.addrs[0]}/v1/status"
                ) as resp:
                    status_body = await resp.json()
            assert body["census"]["enabled"]
            (t,) = body["traces"]
            assert t["trace_id"] == tid_slow
            assert set(t["stages"]) >= set(E2E_STAGES)
            assert t["spans"][0]["stage"] == "write"  # start-ordered
            # SLO exemplars name the kept trace on its breached stage
            assert tid_slow in slo_body["stages"]["apply"]["slowest_trace_ids"]
            # /v1/status census block
            assert status_body["traces"]["enabled"]
            assert status_body["traces"]["kept_total"] >= 1
        finally:
            await client_a.close()
            await client_b.close()
            await api_a.stop()
            await api_b.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)
            await shutdown(b)

    asyncio.run(main())

"""Pins for the vectorized local-commit finalize (r14 batch, r21
columnar, r24 native).

1. Randomized equivalence: ALL non-reference engines — the r14/r15
   per-cell emit loop (`CORRO_FINALIZE=vector`), the r21 columnar
   phase B (`CORRO_FINALIZE=columnar`, the default), and the r24 C++
   decision loop (`CORRO_FINALIZE=native`) — must emit
   byte/clock-identical changes AND leave byte-identical data/rows/clock
   tables vs the per-cell reference `_finalize_pending_percell` for ANY
   statement mix — delete/reinsert chains inside one tx, dedupe
   (last-write-wins per cell), pk changes (delete+create), resurrections
   across transactions, multi-table transactions, and affinity
   coercions (numeric-looking TEXT into INTEGER columns, ints/floats
   into TEXT columns: the captured cell must carry the value sqlite
   STORED, not the bound parameter).
2. Statement-shape pin (test_pubsub_perf.py style, via the sqlite trace
   callback): the finalize's READ side is a fixed number of chunked
   IN(...) probes — the SELECT count is EQUAL at 100 and 2000 pending
   cells — and the old per-cell probe shapes (`SELECT cl ... WHERE
   pk = ?`, `SELECT col_version ...`) never execute.  No DDL anywhere
   in the commit path.
3. Per-GROUP shape pin (r21): `finalize_group` over a 4-writer group
   issues exactly the probe/flush statement profile of ONE tx touching
   the same rows — the group pays one chunked probe round and one
   executemany flush round total, not one per member tx.
"""

from __future__ import annotations

import random

import pytest

from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp

SCHEMA = (
    "CREATE TABLE kv (id INTEGER NOT NULL PRIMARY KEY,"
    " a TEXT NOT NULL DEFAULT '', b INTEGER NOT NULL DEFAULT 0);"
    "CREATE TABLE pair (k TEXT NOT NULL, g INTEGER NOT NULL,"
    " v TEXT, PRIMARY KEY (k, g));"
)

SITE = ActorId(bytes([7]) * 16)


def mk_store() -> CrdtStore:
    st = CrdtStore(":memory:", site_id=SITE)
    st.apply_schema_sql(SCHEMA)
    return st


def dump_state(store: CrdtStore) -> dict:
    out = {}
    for tbl in ("kv", "pair"):
        out[tbl] = [
            tuple(r)
            for r in store._conn.execute(f'SELECT * FROM "{tbl}" ORDER BY 1, 2')
        ]
        for suffix in ("__crdt_rows", "__crdt_clock"):
            rows = store._conn.execute(
                f'SELECT * FROM "{tbl}{suffix}" ORDER BY pk'
                + (", cid" if suffix == "__crdt_clock" else "")
            ).fetchall()
            out[tbl + suffix] = [tuple(r) for r in rows]
    out["versions"] = [
        tuple(r)
        for r in store._conn.execute(
            "SELECT site_id, db_version FROM __crdt_db_versions ORDER BY site_id"
        )
    ]
    return out


def random_txs(rng: random.Random, n_txs: int) -> list:
    """A list of transactions; each is a list of (sql, params)."""
    txs = []
    for _ in range(n_txs):
        ops = []
        for _ in range(rng.randint(1, 6)):
            kind = rng.random()
            kv_id = rng.randint(1, 5)
            if kind < 0.35:
                ops.append((
                    "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
                    (kv_id, rng.choice(["x", "y", ""]), rng.randint(0, 9)),
                ))
            elif kind < 0.55:
                ops.append((
                    "UPDATE kv SET a = ?, b = b + 1 WHERE id = ?",
                    (rng.choice(["p", "q"]), kv_id),
                ))
            elif kind < 0.7:
                ops.append(("DELETE FROM kv WHERE id = ?", (kv_id,)))
            elif kind < 0.78:
                # pk change: modeled as delete(old)+create(new)
                ops.append((
                    "UPDATE kv SET id = ? WHERE id = ?",
                    (rng.randint(6, 9), kv_id),
                ))
            elif kind < 0.86:
                # affinity mix (r21): an int bound to TEXT-affinity `a`
                # is stored as text, a numeric-looking string or float
                # bound to INTEGER-affinity `b` is stored as an integer
                # — the captured cell must carry the STORED value in
                # every engine
                ops.append((
                    "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
                    (kv_id, rng.randint(100, 999),
                     rng.choice([str(rng.randint(0, 9)), 3.0, 7])),
                ))
            elif kind < 0.92:
                ops.append((
                    "INSERT OR REPLACE INTO pair (k, g, v) VALUES (?, ?, ?)",
                    (rng.choice(["a", "b"]), rng.randint(1, 3),
                     rng.choice([None, "w", "z"])),
                ))
            else:
                ops.append((
                    "DELETE FROM pair WHERE k = ? AND g = ?",
                    (rng.choice(["a", "b"]), rng.randint(1, 3)),
                ))
        txs.append(ops)
    return txs


def run_engine(monkeypatch, engine: str, txs) -> tuple:
    monkeypatch.setenv("CORRO_FINALIZE", engine)
    st = mk_store()
    all_changes = []
    for ops in txs:
        with st.write_tx(Timestamp.from_unix(len(all_changes) + 1)) as tx:
            for sql, params in ops:
                try:
                    tx.execute(sql, params)
                except Exception:
                    pass  # e.g. pk-change collision: both engines skip alike
            changes, _v, _ls = tx.commit()
        all_changes.append([
            (c.table, c.pk, c.cid, c.val, c.col_version, c.db_version,
             c.seq, c.cl)
            for c in changes
        ])
    dump = dump_state(st)
    st.close()
    return all_changes, dump


@pytest.mark.parametrize("engine", ["vector", "columnar", "native"])
@pytest.mark.parametrize("seed", [1, 7, 23, 99])
def test_finalize_engines_equivalent_to_percell(monkeypatch, seed, engine):
    rng = random.Random(seed)
    txs = random_txs(rng, 30)
    ch_ref, dump_ref = run_engine(monkeypatch, "percell", txs)
    ch_eng, dump_eng = run_engine(monkeypatch, engine, txs)
    assert ch_eng == ch_ref
    assert dump_eng == dump_ref


def test_columnar_wire_cells_identical_to_percell(monkeypatch):
    """The columnar batch encoder must produce the exact per-cell wire
    bytes of the reference path, not just equal field tuples (the
    percell engine leaves wire_cell unstamped; `_cell_bytes` backfills
    it through `write_change_fields`, the single-cell source of
    truth)."""
    from corrosion_tpu.types.codec import _cell_bytes

    rng = random.Random(42)
    txs = random_txs(rng, 20)

    def wire(engine):
        monkeypatch.setenv("CORRO_FINALIZE", engine)
        st = mk_store()
        cells = []
        for i, ops in enumerate(txs):
            with st.write_tx(Timestamp.from_unix(i + 1)) as tx:
                for sql, params in ops:
                    try:
                        tx.execute(sql, params)
                    except Exception:
                        pass
                changes, _v, _ls = tx.commit()
            cells.append([_cell_bytes(c) for c in changes])
        st.close()
        return cells

    assert wire("columnar") == wire("percell")
    assert wire("native") == wire("percell")


def test_native_finalize_falls_back_to_columnar_when_unavailable(monkeypatch):
    """No-compiler hosts (r24): `CORRO_FINALIZE=native` with no loadable
    crdt_batch.so must silently produce the columnar engine's results —
    byte-identical changes and state — while counting each fallback on
    `corro.write.finalize.native.unavailable` so fleet dashboards can
    see hosts running degraded."""
    import corrosion_tpu.native as native_mod
    from corrosion_tpu.runtime.metrics import METRICS

    txs = random_txs(random.Random(5), 12)
    ch_ref, dump_ref = run_engine(monkeypatch, "columnar", txs)

    monkeypatch.setattr(native_mod, "finalize_batch_lib", lambda: None)
    before = METRICS.counter("corro.write.finalize.native.unavailable").value
    ch_nat, dump_nat = run_engine(monkeypatch, "native", txs)
    after = METRICS.counter("corro.write.finalize.native.unavailable").value

    assert ch_nat == ch_ref
    assert dump_nat == dump_ref
    assert after > before


@pytest.mark.parametrize("engine", ["vector", "columnar", "native"])
def test_delete_reinsert_same_tx_equivalence(monkeypatch, engine):
    """The trickiest dedupe path, pinned explicitly: delete + re-insert
    (and insert + delete + re-insert) of the same pk inside ONE tx."""
    txs = [
        [("INSERT INTO kv (id, a, b) VALUES (1, 'x', 1)", ())],
        [
            ("DELETE FROM kv WHERE id = 1", ()),
            ("INSERT INTO kv (id, a, b) VALUES (1, 'y', 2)", ()),
            ("UPDATE kv SET a = 'z' WHERE id = 1", ()),
        ],
        [
            ("INSERT INTO kv (id, a, b) VALUES (2, 'n', 0)", ()),
            ("DELETE FROM kv WHERE id = 2", ()),
            ("INSERT INTO kv (id, a, b) VALUES (2, 'm', 9)", ()),
        ],
        [("DELETE FROM kv WHERE id = 1", ())],
        [("INSERT INTO kv (id, a) VALUES (1, 'back')", ())],  # resurrection
    ]
    ch_ref, dump_ref = run_engine(monkeypatch, "percell", txs)
    ch_eng, dump_eng = run_engine(monkeypatch, engine, txs)
    assert ch_eng == ch_ref
    assert dump_eng == dump_ref


def _commit_trace(n_rows: int) -> list:
    """Trace the commit of one tx that UPDATEs n_rows rows (2 pending
    cells each: a + b) over a pre-seeded table."""
    st = mk_store()
    with st.write_tx(Timestamp.from_unix(1)) as tx:
        for i in range(n_rows):
            tx.execute(
                "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (i, "s", 0)
            )
        tx.commit()
    stmts: list = []
    with st.write_tx(Timestamp.from_unix(2)) as tx:
        tx.execute("UPDATE kv SET a = a || 'x', b = b + 1")
        st._conn.set_trace_callback(stmts.append)
        tx.commit()
    st._conn.set_trace_callback(None)
    st.close()
    return stmts


def test_finalize_statement_shape_independent_of_cell_count():
    small = _commit_trace(50)  # 100 pending cells
    large = _commit_trace(1000)  # 2000 pending cells
    for stmts in (small, large):
        for s in stmts:
            head = s.lstrip().upper()
            assert not head.startswith(("CREATE", "DROP", "ALTER")), (
                f"DDL in the commit path: {s}"
            )
            # the pre-r14 per-cell probes must be extinct
            assert not head.startswith("SELECT CL FROM"), s
            assert not head.startswith("SELECT COL_VERSION"), s

    def selects(stmts):
        return [s for s in stmts if s.lstrip().upper().startswith("SELECT")]

    # O(1) reads: same number of probe SELECTs at 100 and 2000 cells
    assert len(selects(small)) == len(selects(large)), (
        selects(small), selects(large)
    )

    def shapes(stmts):
        # statement text up to the first bound-value interpolation
        return sorted({s.split("(")[0] for s in stmts})

    assert shapes(small) == shapes(large)


def _finalize_group_trace(n_txs: int, rows_per_tx: int) -> list:
    """Trace EXACTLY the finalize_group call for a group of `n_txs`
    sub-transactions updating `rows_per_tx` distinct pre-seeded rows
    each (the r14 leader shape: savepointed sub-txs inside group_tx,
    deferred pendings finalized in one call)."""
    st = mk_store()
    total = n_txs * rows_per_tx
    with st.write_tx(Timestamp.from_unix(1)) as tx:
        for i in range(total):
            tx.execute(
                "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (i, "s", 0)
            )
        tx.commit()
    stmts: list = []
    with st.group_tx():
        items = []
        for j in range(n_txs):
            ts = Timestamp.from_unix(2 + j)
            with st.write_tx(ts, nested=True, savepoint=n_txs > 1) as tx:
                lo = j * rows_per_tx
                tx.execute(
                    "UPDATE kv SET a = a || 'x', b = b + 1"
                    " WHERE id >= ? AND id < ?",
                    (lo, lo + rows_per_tx),
                )
                items.append((tx.commit_deferred(), ts))
        st._conn.set_trace_callback(stmts.append)
        st.finalize_group(items)
        st._conn.set_trace_callback(None)
    st.close()
    return stmts


def test_group_finalize_statement_profile_is_per_group():
    """r21 amortization pin: a 4-writer group finalizing 2 rows per tx
    must issue EXACTLY the statement profile of one tx over the same 8
    rows — one chunked probe round and one executemany flush round for
    the whole group, nothing repeated per member tx.  The only allowed
    per-version statements are the `__corro_state` last-seq rows (one
    per committed db_version by design)."""
    from collections import Counter

    grouped = _finalize_group_trace(4, 2)
    solo = _finalize_group_trace(1, 8)

    def profile(stmts):
        out: Counter = Counter()
        for s in stmts:
            if "__corro_state" in s:
                continue  # per-db_version bookkeeping, excluded above
            out[s.split("(")[0].strip()] += 1
        return out

    assert profile(grouped) == profile(solo), (grouped, solo)
    n_state = sum("__corro_state" in s for s in grouped)
    assert n_state == sum("__corro_state" in s for s in solo) * 4

"""Real agent ↔ kernel-peer bridge: the §2.6 seam end-to-end.

A full event-driven agent (`agent/membership.py`, the production SWIM
path) gossips over a MemNetwork with a population that exists only as
the batched kernel's arrays (`ops/swim.py` via `models/cluster.py`,
fronted by `models/bridge.KernelPeerBridge`). The agent must:

- absorb the whole simulated population through normal SWIM channels
  (FEED on announce + piggyback on ACKs),
- detect kernel-side crashes with its OWN probe/suspicion pipeline —
  crashed virtual members simply go silent, like crashed processes.
"""

import asyncio

from corrosion_tpu.models.bridge import KernelPeerBridge, sim_actor_id
from corrosion_tpu.models.cluster import ClusterSim
from corrosion_tpu.net.gossip_codec import MemberState
from corrosion_tpu.net.mem import MemNetwork

from tests.test_agent import boot, count_rows, insert, wait_progress, wait_until

N_SIM = 192


def test_agent_absorbs_kernel_population_and_detects_crashes():
    async def main():
        net = MemNetwork(seed=11)
        sim = ClusterSim(N_SIM, seed=3)
        # gossip_down=False: crashed virtual members are only SILENT —
        # the agent has to detect them with its own probe pipeline
        bridge = KernelPeerBridge(net, sim, seed=5, gossip_down=False)
        bridge.start()

        agent = await boot(net, "agent-real")
        ms = agent.membership
        try:
            # join via one virtual member; the FEED + ACK piggyback
            # epidemic must teach the agent the whole population
            await ms.announce(bridge.addr(0))
            assert await wait_until(
                lambda: ms.cluster_size >= N_SIM + 1, timeout=60.0
            ), f"only {ms.cluster_size} of {N_SIM + 1} members learned"

            # the kernel keeps running underneath
            sim.step(5)
            bridge.refresh()

            # crash three simulated members: silence, not notification
            dead = [7, 63, 150]
            for j in dead:
                bridge.crash(j)

            dead_ids = {sim_actor_id(j) for j in dead}

            def all_detected() -> bool:
                # the agent's own pipeline ends in eviction: DOWN members
                # move from `members` into `downed`
                return all(
                    i in ms.downed
                    or (
                        i in ms.members
                        and ms.members[i].state == MemberState.SUSPECT
                    )
                    for i in dead_ids
                )

            assert await wait_until(all_detected, timeout=60.0)
            # ... and fully evicted shortly after suspicion expires
            assert await wait_until(
                lambda: dead_ids <= set(ms.downed), timeout=60.0
            )

            # zero false positives: nothing else was downed
            assert set(ms.downed) == dead_ids
        finally:
            from corrosion_tpu.agent.run import shutdown

            await shutdown(agent)
            await bridge.stop()

    asyncio.run(main())


def test_replication_alongside_simulated_population():
    """Two real agents replicate CRDT writes while both absorb and track
    a kernel-simulated population — the production stack and the tpu-sim
    world coexisting on one gossip plane."""

    async def main():
        n_sim = 96
        net = MemNetwork(seed=21)
        sim = ClusterSim(n_sim, seed=4)
        bridge = KernelPeerBridge(net, sim, seed=6)
        bridge.start()

        a = await boot(net, "agent-a")
        b = await boot(net, "agent-b", bootstrap=("agent-a",))
        try:
            # join the simulated world via one virtual member
            await a.membership.announce(bridge.addr(0))

            # progress-based bounds throughout (r4 weak #6/#8): a loaded
            # host slows the soak but only a genuine STALL fails it

            # real->real replication keeps working.  Delivery to b is
            # probabilistic once the 96 virtual members flood the view:
            # eager broadcast fans out to a random handful of ~97 peers
            # per (re)transmission and the sync backstop picks uniform-
            # random peers (mostly virtual ones that close bi streams) —
            # so rows can legitimately take ~n_sim sync rounds to land.
            # Progress = probe-loop activity (monotone while the agents
            # live); the cap is the real bound, same discipline as the
            # crash-detection wait below (r12 — this wait's old
            # (rows, cluster_size) tuple froze during legitimate sync
            # retries and tripped the 30 s stall under full-suite load).
            await insert(a, 1, "hello")
            assert await wait_progress(
                lambda: count_rows(b) == 1,
                lambda: (
                    count_rows(b), a.membership.cluster_size,
                    a.membership._probe_no, b.membership._probe_no,
                ),
                stall=60.0, cap=300.0,
            )

            # BOTH real agents absorb the population (b learns the sim
            # members only through a's piggyback — transitive spread)
            assert await wait_progress(
                lambda: a.membership.cluster_size >= n_sim + 2,
                lambda: a.membership.cluster_size,
            ), f"a stalled at {a.membership.cluster_size}/{n_sim + 2}"
            assert await wait_progress(
                lambda: b.membership.cluster_size >= n_sim + 2,
                lambda: b.membership.cluster_size,
            ), f"b stalled at {b.membership.cluster_size}/{n_sim + 2}"

            # a crashed sim member is evicted from BOTH agents' tables
            # (bridge gossips the kernel's ground-truth DOWN by default)
            bridge.crash(17)
            gone = sim_actor_id(17)
            assert await wait_progress(
                lambda: gone in a.membership.downed
                and gone in b.membership.downed,
                # suspicion progress isn't externally visible until
                # eviction lands, so progress = probe-loop activity
                # (monotone while the agents are alive) + evictions
                lambda: (
                    len(a.membership.downed), len(b.membership.downed),
                    a.membership._probe_no, b.membership._probe_no,
                ),
                # probe activity never stalls while agents live, so the
                # cap is the real bound here: detection normally lands in
                # seconds, 300 s means genuinely broken
                stall=60.0, cap=300.0,
            )
            # ... while replication still flows (same probabilistic
            # delivery as the first write: probe counters as progress)
            await insert(a, 2, "after-churn")
            assert await wait_progress(
                lambda: count_rows(b) == 2,
                lambda: (
                    count_rows(b),
                    a.membership._probe_no, b.membership._probe_no,
                ),
                stall=60.0, cap=300.0,
            )
        finally:
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)
            await shutdown(b)
            await bridge.stop()

    asyncio.run(main())

"""agent/remediation.py: the supervised remediation plane (r22).

Three layers:

1. GATE PROTOCOL (fake clocks + fake engines): sustain, cooldown,
   precondition, Lifeguard deferral-until-cluster-consensus, and the
   `enabled=false` observe-only kill-switch — each produces its typed,
   drill-stamped history event exactly once per firing episode.
2. ACTUATOR UNITS: slo-burn sheds the clogged sink tier with the typed
   `SubLagging` terminal the r16 client resume path already handles.
3. INTEGRATION (real agents over MemNetwork): view-divergence →
   targeted-sync actually converges a node that missed writes, and
   store-faults → drain+refuse-bulk drains the matcher homes with
   clean terminals while the node stays read-available — then the
   revert clears the refuse flags when the rule resolves.
"""

from __future__ import annotations

import asyncio
import time
from types import SimpleNamespace

from corrosion_tpu.agent.remediation import (
    Actuator,
    RemediationSupervisor,
    default_actuators,
)
from corrosion_tpu.runtime.alerts import DEFAULT_ACTIONS
from corrosion_tpu.runtime.config import RemediationConfig


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeAlerts:
    """Just the two reads the supervisor makes."""

    def __init__(self, firing=(), health=0.0):
        self.firing = list(firing)
        self.health = health

    def firing_snapshot(self):
        return list(self.firing)

    def health_score(self):
        return self.health


class FakeObs:
    def __init__(self, rollup):
        self.rollup = rollup

    def cluster_alerts(self):
        return {"rollup": self.rollup}


def firing(rule, secs=60.0):
    return {"rule": rule, "severity": "page", "firing_secs": secs,
            "since_wall": 1.0, "value": 1.0, "drill": None}


def fake_agent(**kw):
    ns = SimpleNamespace(
        actor_id="me-node", alerts=FakeAlerts(), observatory=None,
        subs=None, bulk_refuse_until=0.0,
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def probe_supervisor(agent, cfg, cooldown=10.0, sustain=0.0):
    """A supervisor with ONE synthetic actuator bound to
    view-divergence, recording its runs in `runs`."""
    runs = []

    async def act(a):
        runs.append(1)
        return {"ok": len(runs)}

    sup = RemediationSupervisor(
        agent, cfg=cfg,
        actuators={
            "probe": Actuator(
                name="probe", rule="view-divergence", summary="t",
                cooldown_secs=cooldown, act=act, sustain_secs=sustain,
            )
        },
        bindings={"view-divergence": "probe"},
        clock=Clock(), wall=Clock(5000.0),
    )
    return sup, runs


def modes(sup):
    return [h["mode"] for h in sup.report()["history"]]


# -- gate protocol ----------------------------------------------------------


def test_kill_switch_records_would_act_once_per_episode():
    agent = fake_agent(alerts=FakeAlerts([firing("view-divergence")]))
    sup, runs = probe_supervisor(agent, RemediationConfig(enabled=False))

    async def main():
        await sup.tick()
        await sup.tick()  # same episode: no duplicate row
        assert runs == []
        assert modes(sup) == ["would_act"]
        ev = sup.report()["history"][0]
        assert ev["action"] == "probe"
        assert ev["rule"] == "view-divergence"
        assert ev["cooldown_secs"] == 10.0
        assert "kill_switch" in ev["detail"]
        # episode ends and refires: a fresh would_act row
        agent.alerts.firing = []
        await sup.tick()
        agent.alerts.firing = [firing("view-divergence")]
        await sup.tick()
        assert modes(sup) == ["would_act", "would_act"]
        assert sup.census()["armed"] is False

    asyncio.run(main())


def test_cooldown_gates_repeat_acts():
    agent = fake_agent(alerts=FakeAlerts([firing("view-divergence")]))
    sup, runs = probe_supervisor(
        agent, RemediationConfig(enabled=True), cooldown=10.0
    )

    async def main():
        await sup.tick()
        await sup.tick()  # inside the cooldown window
        assert runs == [1]
        sup._clock.t += 11.0  # past the cooldown
        await sup.tick()
        assert runs == [1, 1]
        assert modes(sup) == ["acted", "acted"]

    asyncio.run(main())


def test_sustain_holds_young_firings():
    agent = fake_agent(
        alerts=FakeAlerts([firing("view-divergence", secs=1.0)])
    )
    sup, runs = probe_supervisor(
        agent, RemediationConfig(enabled=True), sustain=5.0
    )

    async def main():
        await sup.tick()
        assert runs == [] and modes(sup) == []
        agent.alerts.firing = [firing("view-divergence", secs=6.0)]
        await sup.tick()
        assert runs == [1]

    asyncio.run(main())


def test_bad_health_defers_until_cluster_consensus():
    """The Lifeguard pin: a node whose local health score is past
    `defer_health` must NOT act on its own telemetry — it records a
    typed `deferred` event and holds until the digest-merged rollup
    shows the same rule firing on ANOTHER node."""
    agent = fake_agent(
        alerts=FakeAlerts([firing("view-divergence")], health=0.9),
        observatory=FakeObs(
            {"view-divergence": {"firing": ["me-node"]}}
        ),
    )
    sup, runs = probe_supervisor(agent, RemediationConfig(enabled=True))

    async def main():
        # only our own sick digest says so: defer, no action
        await sup.tick()
        await sup.tick()
        assert runs == []
        assert modes(sup) == ["deferred"]
        assert sup.report()["history"][0]["detail"]["health_score"] == 0.9
        # no observatory at all: same self-distrust
        agent.observatory = None
        await sup.tick()
        assert runs == []
        # a second node's digest confirms the rule: consensus — act
        agent.observatory = FakeObs(
            {"view-divergence": {"firing": ["me-node", "peer-node"]}}
        )
        await sup.tick()
        assert runs == [1]
        assert modes(sup) == ["deferred", "acted"]

    asyncio.run(main())


def test_default_registry_binds_every_ruled_action():
    cfg = RemediationConfig()
    acts = default_actuators(cfg)
    assert set(DEFAULT_ACTIONS.values()) == set(acts)
    for rule, name in DEFAULT_ACTIONS.items():
        assert acts[name].rule == rule
        assert acts[name].cooldown_secs > 0
    # the drain actuator is the one with standing side effects: it
    # must carry the revert hook
    assert acts["drain-refuse-bulk"].revert is not None
    assert acts["shed-laggards"].sustain_secs == cfg.slo_sustain_secs


# -- actuator units ---------------------------------------------------------


def test_slo_burn_sheds_laggards_with_typed_lagging_frame():
    """slo-burn → shed: the clogged sink ends with the SAME typed
    `SubLagging` terminal the lag bounds produce — the r16 client
    resume path needs no new case."""
    from corrosion_tpu.pubsub.fanout import FanoutWriter, StreamSink, SubLagging

    async def main():
        fan = FanoutWriter()
        sink = StreamSink(1 << 20, 1024)
        sink.hold = False
        sink.pending.append((b"x" * 10, 0))
        sink.pending_bytes = 10
        fan._clogged[id(sink)] = sink
        agent = fake_agent(
            alerts=FakeAlerts([firing("slo-burn", secs=60.0)]),
            subs=SimpleNamespace(fanout=fan),
        )
        cfg = RemediationConfig(enabled=True)
        sup = RemediationSupervisor(agent, cfg=cfg)
        await sup.tick()
        assert sink.done.done()
        shed = sink.done.result()
        assert isinstance(shed, SubLagging)
        assert shed.lag_bytes == 10 and shed.lag_batches == 1
        (ev,) = sup.report()["history"]
        assert ev["mode"] == "acted" and ev["action"] == "shed-laggards"
        assert ev["detail"]["laggards_shed"] == 1
        assert fan.clogged_count() == 0

    asyncio.run(main())


def test_shed_refuses_with_no_laggards():
    from corrosion_tpu.pubsub.fanout import FanoutWriter

    async def main():
        agent = fake_agent(
            alerts=FakeAlerts([firing("slo-burn", secs=60.0)]),
            subs=SimpleNamespace(fanout=FanoutWriter()),
        )
        sup = RemediationSupervisor(
            agent, cfg=RemediationConfig(enabled=True)
        )
        await sup.tick()
        (ev,) = sup.report()["history"]
        assert ev["mode"] == "refused"
        assert "no laggard" in ev["detail"]["reason"]

    asyncio.run(main())


def test_acts_are_flight_recorded():
    """Acted events ride the process flight recorder, so incident
    dumps carry the remediation decision trail."""
    from corrosion_tpu.pubsub.fanout import FanoutWriter, StreamSink
    from corrosion_tpu.runtime.records import FLIGHT

    async def main():
        fan = FanoutWriter()
        sink = StreamSink(1 << 20, 1024)
        sink.hold = False
        sink.pending.append((b"y" * 4, 0))
        sink.pending_bytes = 4
        fan._clogged[id(sink)] = sink
        agent = fake_agent(
            alerts=FakeAlerts([firing("slo-burn", secs=60.0)]),
            subs=SimpleNamespace(fanout=fan),
        )
        sup = RemediationSupervisor(
            agent, cfg=RemediationConfig(enabled=True)
        )
        before = len(FLIGHT.window(4096, kernel="remediation"))
        await sup.tick()
        frames = FLIGHT.window(4096, kernel="remediation")
        assert len(frames) > before
        assert frames[-1]["events"].get("shed") == 1

    asyncio.run(main())


# -- integration: the real alert→action paths -------------------------------


def test_divergence_targeted_sync_converges():
    """view-divergence → targeted-sync: a node that missed writes (its
    periodic sync_loop backed off out of the test window) converges
    after ONE supervisor tick drives the targeted round."""
    from corrosion_tpu.agent.run import shutdown
    from corrosion_tpu.net.mem import MemNetwork
    from tests.test_agent import (
        boot,
        count_rows,
        fast_config,
        insert,
        wait_until,
    )

    async def main():
        net = MemNetwork(seed=22)
        cfg_a = fast_config("agent-a")
        a = await boot(net, "agent-a", cfg=cfg_a)
        rows = 8
        for i in range(rows):
            await insert(a, i + 1, f"pre-join-{i}")
        # A's broadcast backlog must DRAIN before B joins (the pending
        # heap resends for ~1.4 s at the n=1 transmission budget) or
        # the backlog floods B on join and the divergence premise dies
        from corrosion_tpu.runtime.metrics import METRICS

        def pending_count():
            for _k, n, _l, v in METRICS.snapshot():
                if n == "corro.broadcast.pending.count":
                    return v
            return 0.0

        # settle nap first: a fresh change takes one broadcast-loop
        # interval to even reach the pending heap's gauge
        await asyncio.sleep(0.3)
        assert await wait_until(lambda: pending_count() == 0)
        # B joins AFTER the writes; its own sync loop is pushed out of
        # the test window so only the actuator can repair the gap
        cfg_b = fast_config("agent-b", bootstrap=["agent-a"])
        cfg_b.perf.sync_interval_min_secs = 120.0
        cfg_b.perf.sync_interval_max_secs = 120.0
        cfg_b.remediation.enabled = True
        b = await boot(net, "agent-b", cfg=cfg_b)
        try:
            assert await wait_until(
                lambda: any(
                    aid != b.actor_id for aid in b.members.states
                )
            )
            # the divergence is real: B is missing rows (a straggler
            # broadcast resend may have landed one — the premise only
            # needs a gap for the actuator to close)
            assert count_rows(b) < rows
            assert b.remediation is not None
            b.alerts.firing_snapshot = (
                lambda: [firing("view-divergence")]
            )
            b.alerts.health_score = lambda: 0.0
            await b.remediation.tick()
            assert await wait_until(lambda: count_rows(b) == rows), (
                count_rows(b)
            )
            # the supervisor LOOP also ticks (enabled=True) — in a slow
            # window it may act a second time after the cooldown, so
            # assert over every acted event instead of unpacking one
            history = b.remediation.report()["history"]
            acted = [e for e in history if e["mode"] == "acted"]
            assert acted, history
            for ev in acted:
                assert ev["action"] == "targeted-sync"
                assert ev["rule"] == "view-divergence"
                assert ev["cooldown_secs"] > 0
            assert any(
                e["detail"]["changes_received"] > 0 for e in acted
            ), acted
        finally:
            for ag in (a, b):
                await shutdown(ag)

    asyncio.run(main())


def test_store_faults_drain_refuse_bulk_stays_read_available():
    """store-faults → drain-refuse-bulk: matcher homes drain with the
    clean typed terminal, new streams and bulk transfers are refused,
    reads keep working — and the revert clears the flags when the rule
    resolves."""
    from corrosion_tpu.agent.run import shutdown
    from corrosion_tpu.net.mem import MemNetwork
    from tests.test_agent import boot, count_rows, fast_config, insert

    async def main():
        net = MemNetwork(seed=23)
        cfg = fast_config("agent-a")
        cfg.remediation.enabled = True
        a = await boot(net, "agent-a", cfg=cfg)
        try:
            await insert(a, 1, "kept")
            handle, created = await a.subs.get_or_insert(
                "SELECT id, text FROM tests"
            )
            assert created
            q = handle.attach()
            assert a.remediation is not None
            a.alerts.firing_snapshot = lambda: [firing("store-faults")]
            a.alerts.health_score = lambda: 0.0
            await a.remediation.tick()
            # homes drained, subscriber released with the clean terminal
            assert a.subs.handles() == []
            assert await asyncio.wait_for(q.get(), 5) is None
            # refuse-bulk armed on both planes, typed admission refusal
            now = time.monotonic()
            assert a.bulk_refuse_until > now
            assert a.subs.refuse_until > now
            reason = a.subs.admission_reject()
            assert reason and "refuse-bulk" in reason
            # Prime CCL: capacity shrank, reads did NOT stall
            assert count_rows(a) == 1
            (ev,) = a.remediation.report()["history"]
            assert ev["mode"] == "acted"
            assert ev["action"] == "drain-refuse-bulk"
            assert ev["detail"]["homes_drained"] == 1
            # rule resolves → revert clears the standing flags early
            a.alerts.firing_snapshot = lambda: []
            await a.remediation.tick()
            assert a.bulk_refuse_until == 0.0
            assert a.subs.refuse_until == 0.0
            assert a.subs.admission_reject() is None
            assert modes(a.remediation) == ["acted", "reverted"]
        finally:
            await shutdown(a)

    asyncio.run(main())

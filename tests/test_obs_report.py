"""`scripts/obs_report.py` renders the event counters end-to-end from a
PViewClusterSim run (acceptance pin, r7).  Tiny shape: the point is the
plumbing (sim → registry → table render → artifact), not the workload."""

from __future__ import annotations

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_obs_report_renders_event_counters(tmp_path):
    out = tmp_path / "OBS_REPORT_test.md"
    env = dict(
        os.environ,
        OBS_REPORT_N="256",
        OBS_REPORT_SLOTS="32",
        OBS_REPORT_MAX_TICKS="400",
        # each cross-node write pays the matcher's 600 ms candidate
        # batching window — keep the tier-1 replica tiny
        OBS_REPORT_E2E_WRITES="5",
        # r12 cluster section: the two-node partition replay's wall is
        # dominated by detection/heal rounds, writes just seed the
        # digests' stage histograms — trim to the minimum
        OBS_REPORT_CLUSTER_WRITES="3",
        OBS_REPORT_OUT=str(out),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py")],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    text = out.read_text()
    assert "platform=cpu" in text  # forced: points must be comparable
    assert "corro.kernel.events.total" in text
    # the pview lane rendered with real totals
    m = re.search(r"^pview\s+gossip_emitted\s+(\d+)", text, re.M)
    assert m and int(m.group(1)) > 0, text
    assert re.search(r"^pview\s+merge_won\s+(\d+)", text, re.M)
    # the phase-gauge family renders in the same artifact
    assert "corro.kernel.phase.seconds" in text
    assert re.search(r"^pview\s+tick\s+", text, re.M)
    # r8: the flight-recorder section renders tick-resolved sparklines
    assert "## flight recorder" in text
    m = re.search(
        r"^gossip_emitted\s+\d+\s+\d+\s+\d+\s+([▁▂▃▄▅▆▇█]+)$", text, re.M
    )
    assert m, "no gossip_emitted sparkline row"
    assert re.search(r"^census_alive\s+", text, re.M)
    assert re.search(r"^suspect_raised\s+", text, re.M)
    # r11: the SLO latency section renders non-empty per-stage rows from
    # a real write→event workload plus the canary round-trip sparkline
    assert "## SLO latency plane" in text
    for stage in ("broadcast", "apply", "match", "deliver", "total"):
        m = re.search(rf"^{stage}\s+(\d+)\s", text, re.M)
        assert m and int(m.group(1)) > 0, f"stage {stage} has no samples"
    assert "## canary round trips" in text
    assert re.search(r"^trend [▁▂▃▄▅▆▇█]+$", text, re.M)
    # r12: the cluster-observatory section renders the coverage table
    # and a divergence timeline whose episode actually opened + cleared
    assert "## cluster observatory" in text
    m = re.search(r"partition detected in (\d+) digest rounds", text)
    assert m and int(m.group(1)) >= 1, "no detection headline"
    assert "digest coverage at full aggregation" in text
    assert re.search(r"\bOPEN\b", text), "episode never rendered OPEN"
    assert re.search(r"^episode trend [▁▂▃▄▅▆▇█]+$", text, re.M)
    # both nodes' coverage rows rendered fresh
    assert len(re.findall(r"^\S+\s+True\s+\d+\s+", text, re.M)) == 2
    # r20: the alerting plane renders the default rule pack's states
    # over a live TSDB sample of this run's registry
    assert "## alerting plane" in text
    for rule in ("slo-burn", "loop-lag", "view-divergence", "store-faults"):
        assert re.search(rf"^{rule}\s+\w+\s+\w+\s+", text, re.M), (
            f"rule {rule} not rendered"
        )
    assert re.search(r"tsdb: \d+ series / \d+ points", text)

"""Partial-view SWIM kernel: dense-equivalence, convergence, eviction.

The bounded hash-slot kernel (`ops/swim_pview.py`) must (a) be
bit-equivalent to the dense kernel when run in identity-hash mode with
slots == n — the dense kernel is its K = n special case — and (b)
converge to stable in-degree coverage with a genuinely bounded table
(slots << n), which is what carries the design past the dense [N, N]
memory wall (VERDICT r2 missing #5).
"""

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.ops import swim, swim_pview


def _dense_from_pview(params, packed, t):
    """Reconstruct the dense [N, N] view from an identity-hash slot table."""
    rows = jnp.arange(params.n, dtype=jnp.int32)[:, None]
    subj, key = swim_pview._unpack(params, packed, rows, t)
    n = params.n
    view = jnp.zeros((n, n), dtype=jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], subj.shape)
    occupied = key > 0
    return view.at[
        jnp.where(occupied, rows, 0), jnp.where(occupied, subj, 0)
    ].max(jnp.where(occupied, key, 0))


def test_identity_hash_bit_parity_with_dense():
    """slots == n + identity hash ⇒ the pview tick IS the dense tick:
    same rng stream, same merges, same FSM trajectory, bit for bit."""
    n = 64
    # FSM/gossip params must match pairwise — bounded-mode defaults are
    # tuned differently (announce/antientropy), so pin them explicitly.
    # gossip_mode is pinned to "pick": the pview kernel's delivery is
    # pick-shaped (per-member target selection into hash slots); the
    # dense default flipped to "shift" in r5, which has no bounded-view
    # counterpart — this parity pin is about the FSM/merge rules, which
    # are mode-independent
    dp = swim.SwimParams(
        n=n, feeds_per_tick=2, feed_entries=16, announce_period=8,
        antientropy=2, gossip_mode="pick",
    )
    # tick_mode/gossip_mode pinned to the round-5 formulation: the
    # bit-parity contract is defined against the sequential-feed,
    # pick-delivery tick (the r6 "fused"/"shift" default restructure is
    # convergence-pinned separately, not bit-pinned — see
    # test_fused_tick_statistical_parity_with_r5)
    pp = swim_pview.PViewParams(
        n=n, slots=n, identity_hash=True, feeds_per_tick=2, feed_entries=16,
        announce_period=8, antientropy=2, tick_mode="r5", gossip_mode="pick",
    )
    rng = jax.random.PRNGKey(0)
    ds = swim.init_state(dp, rng)
    ps = swim_pview.init_state(pp, rng)

    # crash one member part-way to exercise suspect/down/refute paths too
    for i in range(30):
        step_rng = jax.random.fold_in(jax.random.PRNGKey(7), i)
        if i == 10:
            ds = swim.set_alive(ds, 5, False)
            ps = swim_pview.set_alive(ps, 5, False)
        if i == 20:
            ds = swim.set_alive(ds, 5, True)
            ps = swim_pview.set_alive(ps, 5, True)
        ds = swim.tick(ds, step_rng, dp)
        ps = swim_pview.tick(ps, step_rng, pp)

    recon = _dense_from_pview(pp, ps.slot_packed, ps.t)
    assert jnp.array_equal(recon, ds.view), "view trajectories diverged"
    assert jnp.array_equal(ps.inc, ds.inc)
    assert jnp.array_equal(ps.buf_subj, ds.buf_subj)
    assert jnp.array_equal(ps.buf_key, ds.buf_key)
    assert jnp.array_equal(ps.probe_phase, ds.probe_phase)
    assert jnp.array_equal(ps.probe_subj, ds.probe_subj)
    assert jnp.array_equal(ps.susp_subj, ds.susp_subj)
    # r7: the device telemetry lane is part of the bit-parity contract —
    # both kernels must have COUNTED identically, not just merged
    # identically (test_kernel_telemetry.py pins the per-tick version)
    assert jnp.array_equal(ps.events, ds.events)


def test_bounded_view_converges():
    """slots = n/8: every live member ends up known by ≈ the expected
    number of observers, with zero false positives."""
    n, k = 512, 64
    pp = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=4, feed_entries=16
    )
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    stats = None
    for chunk in range(20):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 25)
        stats = swim_pview.membership_stats(state, pp)
        if stats["pv_coverage"] >= 0.999 and stats["min_in_degree"] > 0:
            break
    assert stats["pv_coverage"] >= 0.999, stats
    assert stats["min_in_degree"] > 0, stats
    assert stats["false_positive"] == 0.0, stats
    # the table really is bounded: occupancy can never exceed 1, and the
    # mean in-degree is capped by the slot budget, not by n
    assert stats["occupancy"] <= 1.0
    assert stats["mean_in_degree"] <= k


def test_detects_crash_with_bounded_view():
    n, k = 256, 64
    pp = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=4, feed_entries=16)
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 25)
    state = swim_pview.set_alive(state, 3, False)
    # dead member must eventually be marked down by holders of its entry
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    rows = jnp.arange(pp.n, dtype=jnp.int32)[:, None]
    subj, key_ = swim_pview._unpack(pp, state.slot_packed, rows, state.t)
    holds_3 = (subj == 3) & (key_ > 0) & state.alive[:, None]
    down_3 = holds_3 & (swim.key_prec(key_) == swim.PREC_DOWN)
    n_holds = int(jnp.sum(jnp.any(holds_3, axis=1)))
    n_down = int(jnp.sum(jnp.any(down_3, axis=1)))
    assert n_holds > 0
    # every live holder of member 3's entry has it marked down
    assert n_down == n_holds, (n_down, n_holds)


def test_refutation_with_bounded_view():
    """A suspected-but-alive member refutes: no live member may end up
    holding a suspect/down entry about it at its current incarnation."""
    n, k = 256, 64
    pp = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=4, feed_entries=16)
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    for _ in range(6):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 25)
    # crash + quick restart: stale down-entries must be refuted away
    state = swim_pview.set_alive(state, 7, False)
    for _ in range(3):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    state = swim_pview.set_alive(state, 7, True)
    for _ in range(10):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    stats = swim_pview.membership_stats(state, pp)
    assert stats["false_positive"] == 0.0, stats


def test_own_entry_pinned():
    """A member's own record survives any collision pressure."""
    n, k = 512, 16  # heavy pressure: 512 subjects → 16 slots
    pp = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=2, feed_entries=8)
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    for _ in range(10):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    self_idx = jnp.arange(n, dtype=jnp.int32)
    selfk = swim_pview._lookup(pp, state.slot_packed, self_idx, state.t)
    assert bool(jnp.all(selfk > 0)), "own entry evicted somewhere"
    assert bool(jnp.all(swim.key_prec(selfk) == swim.PREC_ALIVE))


def test_inc_cap_math():
    assert swim_pview.inc_cap(1_000_000) >= 500
    assert swim_pview.inc_cap(262_144) >= 2000
    # packed word stays in int32 at the cap
    for n in (1_000_000, 262_144, 1000):
        cap = swim_pview.inc_cap(n)
        n2 = swim_pview._pow2(n)
        kc = swim_pview._keycap(n)
        worst_key = swim.make_key(cap, swim.PREC_DOWN)
        assert worst_key < kc
        assert (n2 - 1) * kc + worst_key < 2**31


def test_retention_fairness_under_load():
    """Bucket load 16 (n/slots): the XOR-mask tie-break must keep slot
    retention fair — an additive rotation pins each subject's win share
    to its fixed bucket-gap and some members starve (measured plateau:
    pv_coverage ~0.97 with members at in-degree 0-17 at this load).
    Gate: the absolute quorum floor every live member needs for robust
    SWIM probing, plus no false positives."""
    n, k = 1024, 64
    pp = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=4, feed_entries=16)
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    mins = []
    stats = {}
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 25)
        stats = swim_pview.membership_stats(state, pp)
        mins.append(stats["min_in_degree"])
    tail = sorted(mins[-4:])
    assert stats["false_positive"] == 0.0, stats
    assert min(mins[-4:]) > 0, mins  # nobody extinct in steady state
    assert tail[len(tail) // 2] >= 8, mins  # median tail at the quorum floor
    assert stats["pv_coverage"] >= 0.97, stats


def test_fingers_seed_mode_pview():
    """Finger bootstrap for the bounded partial view: seeds the correct
    hash slots (own entry + every power-of-two offset peer) and boots to
    quorum with zero false positives."""
    n, k = 256, 64
    params = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=4,
                                    feed_entries=16)
    st = swim_pview.init_state(
        params, jax.random.PRNGKey(0), seed_mode="fingers"
    )
    # member 0 must know itself and each finger peer (entries land in
    # the peers' hash slots; collisions can only merge, not vanish,
    # because all seeds share the same key and the max keeps one)
    offs = [int(o) for o in swim.finger_offsets(n)]
    subj, key = swim_pview._unpack(
        params, st.slot_packed[:1], jnp.zeros((1, 1), jnp.int32), 0
    )
    known = {int(s) for s, valid in zip(subj[0], key[0] > 0) if valid}
    expected = {0} | {o % n for o in offs}
    # every expected subject present unless evicted by a same-slot
    # sibling (same key: max picks the larger masked subject)
    missing = expected - known
    for m in missing:
        h = int(swim_pview._hash(params, jnp.int32(m)))
        others = [s for s in expected if s != m
                  and int(swim_pview._hash(params, jnp.int32(s))) == h]
        assert others, f"subject {m} missing without a slot collision"

    rng = jax.random.PRNGKey(1)
    state = st
    stats = {}
    for _ in range(16):
        rng, kk = jax.random.split(rng)
        state = swim_pview.tick_n_donated(state, kk, params, 10)
        stats = swim_pview.membership_stats(state, params)
        if stats["min_in_degree"] >= 8 and stats["pv_coverage"] >= 0.95:
            break
    assert stats["false_positive"] == 0.0
    assert stats["min_in_degree"] >= 8, stats

    with pytest.raises(ValueError):
        swim_pview.init_state(
            params, jax.random.PRNGKey(0), seed_mode="nope"
        )


def test_incarnation_generation_sites_respect_packed_key_domain():
    """Every incarnation generator clips to min(inc_cap(n), INC_CAP):
    the shared packed buffer merge (_buffer_merge) decodes keys through
    a 15-bit field, so a generated key may never exceed
    make_key(INC_CAP, 3) — the regression the r4 review caught when
    pview briefly generated inc_cap(n)-sized incarnations into it."""
    import jax.numpy as jnp

    from corrosion_tpu.ops import swim

    n = 64
    params = swim_pview.PViewParams(n=n, slots=32)
    state = swim_pview.init_state(params, jax.random.PRNGKey(0))
    hostile = state._replace(
        inc=jnp.full((n,), 10**6, dtype=jnp.int32)
    )
    bumped = swim_pview.set_alive_many(hostile, jnp.arange(n), True)
    assert int(jnp.max(bumped.inc)) <= swim.INC_CAP
    bumped1 = swim_pview.set_alive(hostile, 3, True)
    assert int(bumped1.inc[3]) <= swim.INC_CAP
    # dense kernel restart site has the same clamp
    dparams = swim.SwimParams(n=n)
    dstate = swim.init_state(dparams, jax.random.PRNGKey(0))
    dh = dstate._replace(inc=jnp.full((n,), 10**6, dtype=jnp.int32))
    db = swim.set_alive(dh, 5, True)
    assert int(db.inc[5]) <= swim.INC_CAP
    # refutation cap: min(inc_cap, INC_CAP) for every n
    for nn in (64, 1000, 262144, 1048576):
        assert min(swim_pview.inc_cap(nn), swim.INC_CAP) * 4 + 7 < 2**15


def test_fused_tick_statistical_parity_with_r5():
    """The r6 restructured tick (fused merge chain + shift delivery —
    the new defaults) must converge equivalently to the round-5
    formulation it replaces: same bar (pv_coverage >= 0.99, quorum
    in-degree, FP 0), saturated mean in-degree within tolerance.  This
    is the pin the perf work rides on — the restructure changes WHEN
    table reads happen (pre-merge), never WHAT merges win."""
    n, k = 1024, 128
    results = {}
    for tm, gm in (("fused", "shift"), ("r5", "pick")):
        params = swim_pview.PViewParams(
            n=n, slots=k, feeds_per_tick=4, feed_entries=k // 16,
            tie_epoch=512, tick_mode=tm, gossip_mode=gm,
        )
        state = swim_pview.init_state(
            params, jax.random.PRNGKey(0), seed_mode="fingers"
        )
        rng = jax.random.PRNGKey(1)
        st = {}
        converged = False
        for _ in range(30):
            rng, key = jax.random.split(rng)
            state = swim_pview.tick_n_donated(state, key, params, 10)
            st = swim_pview.membership_stats(state, params)
            if (
                st["pv_coverage"] >= 0.99
                and st["min_in_degree"] >= 8
                and st["mean_in_degree"]
                >= swim_pview.saturation_floor(n, k)
                and st["false_positive"] == 0.0
            ):
                converged = True
                break
        assert converged, (tm, gm, st)
        results[tm] = st
    # both formulations saturate the same table: mean in-degree within
    # 2% (both sit at the hash-collision saturation point), occupancy
    # equal at the bounded-table ceiling
    mf, mr = results["fused"]["mean_in_degree"], results["r5"]["mean_in_degree"]
    assert abs(mf - mr) / mr <= 0.02, results
    assert results["fused"]["occupancy"] >= 0.999
    assert results["fused"]["detected"] == results["r5"]["detected"] == 1.0


@pytest.mark.slow
def test_batched_feed_mode_converges():
    """feed_mode="batched" (one merged scatter per tick, picks read the
    pre-feed table) must converge equivalently to "seq" — the flag exists
    for hardware A/Bs (PROFILE.md r4: on CPU it is ~30% SLOWER at 25k;
    scatter LAUNCH count was not the bottleneck).

    slow-marked (r20 tier-1 budget audit): ~29 s — the suite's 2nd
    slowest test for an A/B flag PROFILE.md already measured as
    non-default; "seq" convergence keeps tier-1 coverage via the
    retention/parity tests, "batched" stays covered in the slow lane."""
    n, k = 2048, 256
    for mode in ("seq", "batched"):
        params = swim_pview.PViewParams(
            n=n, slots=k, feeds_per_tick=4, feed_entries=k // 16,
            tie_epoch=512, feed_mode=mode,
        )
        state = swim_pview.init_state(
            params, jax.random.PRNGKey(0), seed_mode="fingers"
        )
        rng = jax.random.PRNGKey(1)
        converged = False
        for _ in range(40):
            rng, key = jax.random.split(rng)
            state = swim_pview.tick_n_donated(state, key, params, 10)
            st = swim_pview.membership_stats(state, params)
            if (
                st["pv_coverage"] >= 0.99
                and st["min_in_degree"] >= 8
                and st["false_positive"] == 0.0
            ):
                converged = True
                break
        assert converged, (mode, st)

"""No-whole-table-copy guard: chipless AOT live-buffer accounting of the
scanned pview tick.

The 1M×2048 single-chip rung was rejected at compile time because XLA's
copy insertion kept ONE whole-table (8.0 GiB) copy alive in the scanned
r5 tick (`copy.326 = copy(state_slot_packed.1)` — PROFILE.md "Round 5:
1M on chip").  The r6 "fused" tick restructure makes every pre-merge
reader materialize against the tick-start table behind an optimization
barrier, then merges in one in-place scatter chain.

What a CPU-only environment can and cannot pin (measured, PROFILE.md
r6): XLA:CPU's scatter expansion double-buffers even programs the TPU
runs fully in place — the DENSE kernel shows 3 view-sized CPU copies at
shapes whose TPU program has none ("Output size 11.94G; shares 11.94G
with arguments").  So "zero copies on CPU" is not assertable; what IS
assertable chiplessly:

1. donation aliasing survives (the output state shares the input's
   buffers — if a change breaks donation, nothing fits anywhere);
2. the fused structure stays STRICTLY better than the r5 formulation
   the chip rejected (fewer whole-table copy instructions in the
   optimized HLO), and its copy count does not regress past the
   measured-good baseline;
3. the analytic live-set model that has to hold on a chip: donated
   table (in place) + feed pull planes + gossip/FSM state + inbox
   planes fits the v5e's 15.75 GB at 1M×2048.

These run via `jit(...).lower(shapes).compile()` — no arrays are ever
allocated, so the 1M-shape case needs compile time, not memory.
`scripts/pview_profile.py` prints the same accounting as a table.
"""

from __future__ import annotations

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from corrosion_tpu.ops import swim_pview  # noqa: E402

V5E_HBM_BYTES = int(15.75 * 2**30)


@pytest.fixture(autouse=True)
def _fresh_compiles():
    """Opt this module out of the persistent compilation cache (r20,
    tests/conftest.py): the structural guards below inspect
    `memory_analysis()` and `as_text()` of the compiled executable, and
    an executable DESERIALIZED from the on-disk cache reports zeroed
    memory stats (alias/argument/temp sizes) and no HLO text — the
    aliasing assert would fail on every warm run.  These shapes are
    unique to this module, so nothing else loses cache hits."""
    from jax._src import compilation_cache as cc

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    # the cache object is a module singleton initialized on first use:
    # once another test has compiled through it, flipping config alone
    # is not enough for THIS process — reset so the next lookup re-reads
    # the (now disabled) config
    cc.reset_cache()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", old)
        cc.reset_cache()


def _aot(n, k, feeds, tick_mode, chunk=2):
    params = swim_pview.PViewParams(
        n=n, slots=k, feeds_per_tick=feeds,
        feed_entries=max(16, k // 16), tie_epoch=512, tick_mode=tick_mode,
    )
    state_shape = jax.eval_shape(
        lambda: swim_pview.init_state(
            params, jax.random.PRNGKey(0), seed_mode="fingers"
        )
    )
    rng_shape = jax.ShapeDtypeStruct((2,), jnp.uint32)
    compiled = (
        jax.jit(
            swim_pview._tick_n_impl,
            static_argnames=("params", "k"),
            donate_argnums=(0,),
        )
        .lower(state_shape, rng_shape, params, chunk)
        .compile()
    )
    ma = compiled.memory_analysis()
    copies = sum(
        1
        for line in compiled.as_text().splitlines()
        if "copy(" in line and f"s32[{n},{k}]" in line
    )
    return ma, copies


@pytest.mark.parametrize("n,k,feeds", [(16384, 1024, 8)])
def test_fused_tick_structurally_beats_r5_and_keeps_donation(n, k, feeds):
    table_b = n * k * 4
    ma_f, copies_f = _aot(n, k, feeds, "fused")
    ma_r, copies_r = _aot(n, k, feeds, "r5")

    # 1. donation aliasing: the whole input state (including the table
    # AND the r8 flight ring — 8 KiB, well over the 64-byte rng
    # allowance, so a ring that stopped aliasing fails here) is shared
    # with the output — alias covers at least table + ring
    from corrosion_tpu.ops.swim import N_FLIGHT_LANES

    ring_b = 128 * N_FLIGHT_LANES * 4  # default ring_ticks × lanes
    assert ma_f.alias_size_in_bytes >= table_b + ring_b, (
        "donated slot table/flight ring no longer alias their output"
    )
    # everything but the rng key should alias
    assert ma_f.argument_size_in_bytes - ma_f.alias_size_in_bytes <= 64

    # 2. the restructure's structural edge over the formulation the chip
    # rejected: strictly fewer whole-table copy instructions, and no
    # regression past the measured-good fused baseline (2 on XLA:CPU —
    # both belong to the CPU-only scatter expansion, see module doc)
    assert copies_f < copies_r, (copies_f, copies_r)
    assert copies_f <= 2, (
        f"fused tick grew whole-table copies: {copies_f} > 2 — a reader "
        "of the table was likely reintroduced after the merge barrier"
    )

    # 3. temp footprint stays bounded relative to the table even under
    # the CPU overcount (catches an accidental third table-sized temp)
    assert ma_f.temp_size_in_bytes <= 3 * table_b + 64 * n


@pytest.mark.slow
def test_1m_2048_live_set_fits_single_chip_budget():
    """The blocker pin at the REAL shape: AOT-compile the fused scanned
    tick at 1M×2048 (the rung the chip rejected) and check the live-set
    model against the v5e budget.  The CPU-only scatter-expansion copies
    are subtracted per the dense-kernel calibration (PROFILE.md r6);
    what remains — donated table + pull planes + state + inbox temps —
    is the set a chip must hold."""
    n, k, feeds = 1_048_576, 2048, 8
    table_b = n * k * 4
    ma, copies = _aot(n, k, feeds, "fused", chunk=1)
    assert copies <= 2, copies
    adjusted_live = (
        ma.argument_size_in_bytes
        + ma.temp_size_in_bytes
        - copies * table_b
    )
    assert adjusted_live < V5E_HBM_BYTES, (
        f"live set {adjusted_live / 2**30:.2f} GiB exceeds the v5e budget "
        f"({copies} CPU-only table copies already excluded)"
    )

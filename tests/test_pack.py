"""pack_columns/unpack_columns roundtrips + byte fixtures.

Fixture bytes are derived from the format spec in the reference
(`klukai-types/src/pubsub.rs:2257-2340`): [n:u8, (intlen<<3|type):u8, ...].
"""

import math

import pytest

from corrosion_tpu.types.pack import pack_columns, unpack_columns


@pytest.mark.parametrize(
    "values",
    [
        [],
        [None],
        [0],
        [1],
        [-1],
        [127],
        [256],
        [2**40 + 7],
        [-(2**62)],
        [1.5],
        [-0.0],
        [""],
        ["hello"],
        ["héllo wörld"],
        [b""],
        [b"\x00\x01\x02"],
        [None, 42, 2.5, "text", b"blob"],
        ["a" * 300],  # 2-byte length
        [b"x" * 70000],  # 3-byte length
    ],
)
def test_roundtrip(values):
    packed = pack_columns(values)
    out = unpack_columns(packed)
    assert len(out) == len(values)
    for a, b in zip(values, out):
        if isinstance(a, float):
            assert math.isclose(a, b) or (a == 0 and b == 0)
        else:
            assert a == b


def test_fixture_bytes():
    # single integer 1: [1, (1<<3)|1=0x09, 0x01]
    assert pack_columns([1]) == bytes([1, 0x09, 0x01])
    # single NULL: [1, 5]
    assert pack_columns([None]) == bytes([1, 5])
    # integer 0 packs with zero bytes: [1, 0x01]
    assert pack_columns([0]) == bytes([1, 0x01])
    # negative ints always take 8 bytes (two's-complement occupancy)
    assert pack_columns([-1]) == bytes([1, (8 << 3) | 1]) + b"\xff" * 8
    # text "ab": [1, (1<<3)|3, 2, 'a', 'b']
    assert pack_columns(["ab"]) == bytes([1, 0x0B, 2]) + b"ab"
    # real 1.0: big-endian IEEE754
    import struct

    assert pack_columns([1.0]) == bytes([1, 2]) + struct.pack(">d", 1.0)


def test_sign_boundary_encode_widens_decode_stays_reference_compatible():
    # The reference's encoder/decoder pair is asymmetric: its writer
    # (pubsub.rs:2315-2340) packs 128..=255 into ONE byte but its reader
    # (bytes::Buf::get_int) sign-extends, so upstream 255 decodes to -1
    # and such pks never round-trip (the matcher temp-table path drops
    # them). Our encoder widens positive values whose top bit would
    # sign-flip — every value round-trips...
    for v in (127, 128, 255, 256, 32767, 32768, 2**31, 2**47):
        assert unpack_columns(pack_columns([v])) == [v]
    # ...while the DECODER stays bug-compatible: a reference node's
    # 1-byte encoding of 255 (count=1, type=(1<<3)|INTEGER, 0xFF) still
    # decodes to the same -1 the reference itself would read.
    foreign = bytes([1, (1 << 3) | 0x01, 0xFF])
    assert unpack_columns(foreign) == [-1]
    foreign = bytes([1, (1 << 3) | 0x01, 0x80])
    assert unpack_columns(foreign) == [-128]
    # text/blob lengths ride the same integer coding: 128+-byte pks
    # round-trip too (upstream raises/misreads these)
    assert unpack_columns(pack_columns(["x" * 200])) == ["x" * 200]
    assert unpack_columns(pack_columns([b"\x01" * 150])) == [b"\x01" * 150]


def test_ordering_is_stable():
    # pk encodings must be comparable as raw bytes for dedupe maps
    a = pack_columns([1, "x"])
    b = pack_columns([1, "x"])
    assert a == b


def test_empty_text_zero_intlen():
    assert pack_columns([""]) == bytes([1, 3])
    assert unpack_columns(bytes([1, 3])) == [""]


def test_truncated_raises():
    with pytest.raises(ValueError):
        unpack_columns(bytes([2, 0x09, 0x01]))  # claims 2 cols, has 1
    with pytest.raises(ValueError):
        unpack_columns(b"")

"""Lifeguard (r9, arXiv:1707.00788): local-health-aware failure detection
in both SWIM kernels and the host Membership, plus the degraded-node
fault surface that proves it.

Pins, in order:
  1. COMPAT — with lhm_max=0 (the default) the Lifeguard knobs are
     INERT: tuning them changes nothing, bit for bit, in either kernel
     (the off mode is the pre-r9 kernel; the PR's golden check also
     diffed it against actual pre-r9 main).
  2. FREE WHEN HEALTHY — lifeguard ON under zero faults produces the
     same trajectory as OFF in every lane except the repurposed
     probe-cooldown deadline.
  3. PARITY — the identity-hash pview tick equals the dense tick with
     lifeguard ON and a degraded member injected (the strongest
     cross-kernel pin now covers the new paths).
  4. A/B — the headline: one flaky member (processing lag) poisons the
     vanilla cluster with false-positive suspicions; lifeguard-on
     collapses them >= 5x while a real crash is still detected within
     2x the vanilla tick count.  Both kernels, seeded.
  5. HOST — Membership LHM ramp/relax, confirmer-set suspicion windows,
     and the buddy refutation path over MemNetwork; per-node fault
     knobs in net/mem.py.
"""

import asyncio
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.agent.membership import (
    MemberState,
    MemberUpdate,
    Membership,
    SwimConfig,
)
from corrosion_tpu.net.mem import LinkFaults, MemNetwork
from corrosion_tpu.ops import swim, swim_pview
from corrosion_tpu.runtime.metrics import KERNEL_EVENTS

from tests.test_membership import FAST, mk_node, wait_until

EV = {name: i for i, name in enumerate(KERNEL_EVENTS)}

LG_FAST = SwimConfig(
    probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0,
    lifeguard=True, lhm_max=8, susp_ceiling=3.0, susp_k=3,
)


# ---------------------------------------------------------------------------
# 1. compat: lhm off => lifeguard knobs are inert (both kernels)
# ---------------------------------------------------------------------------


def test_lifeguard_knobs_inert_when_disabled_dense():
    base = swim.SwimParams(n=48, loss=0.1)
    tuned = swim.SwimParams(
        n=48, loss=0.1, lhm_decay_ticks=3, susp_ceiling=7, susp_k=9
    )
    assert base.lhm_max == 0  # the compat default
    s0 = swim.init_state(base, jax.random.PRNGKey(0))
    s1 = swim.init_state(tuned, jax.random.PRNGKey(0))
    s0 = swim.tick_n(s0, jax.random.PRNGKey(1), base, 8)
    s1 = swim.tick_n(s1, jax.random.PRNGKey(1), tuned, 8)
    for name, a in s0._asdict().items():
        assert jnp.array_equal(a, getattr(s1, name)), f"field {name}"


def test_lifeguard_knobs_inert_when_disabled_pview():
    mk = lambda **kw: swim_pview.PViewParams(  # noqa: E731
        n=64, slots=32, loss=0.1, feeds_per_tick=2, feed_entries=16, **kw
    )
    base, tuned = mk(), mk(lhm_decay_ticks=3, susp_ceiling=7, susp_k=9)
    s0 = swim_pview.init_state(base, jax.random.PRNGKey(0))
    s1 = swim_pview.init_state(tuned, jax.random.PRNGKey(0))
    s0 = swim_pview.tick_n(s0, jax.random.PRNGKey(1), base, 8)
    s1 = swim_pview.tick_n(s1, jax.random.PRNGKey(1), tuned, 8)
    for name, a in s0._asdict().items():
        assert jnp.array_equal(a, getattr(s1, name)), f"field {name}"


def test_lifeguard_free_when_healthy_dense():
    """Lifeguard ON with zero faults: every lane bit-equal to OFF
    except probe_deadline (repurposed as the always-zero cooldown)."""
    off = swim.SwimParams(n=48)
    on = swim.SwimParams(n=48, lhm_max=8)
    s_off = swim.init_state(off, jax.random.PRNGKey(0))
    s_on = swim.init_state(on, jax.random.PRNGKey(0))
    s_off = swim.tick_n(s_off, jax.random.PRNGKey(1), off, 10)
    s_on = swim.tick_n(s_on, jax.random.PRNGKey(1), on, 10)
    differing = {
        name
        for name, a in s_off._asdict().items()
        if not jnp.array_equal(a, getattr(s_on, name))
    }
    assert differing <= {"probe_deadline"}, differing
    assert int(jnp.max(s_on.lhm)) == 0  # nobody got sick


# ---------------------------------------------------------------------------
# 3. identity-hash parity with lifeguard ON + degradation
# ---------------------------------------------------------------------------


def test_identity_hash_parity_with_lifeguard_and_degradation():
    """The dense-equivalence configuration stays BIT-equal with every
    Lifeguard mechanism active and a degraded member injected — the
    r5 parity contract extended over the new paths (events included)."""
    n = 48
    dp = swim.SwimParams(
        n=n, feeds_per_tick=2, feed_entries=16, announce_period=8,
        antientropy=2, gossip_mode="pick", loss=0.1, lhm_max=8,
        suspicion_ticks=4,
    )
    pp = swim_pview.PViewParams(
        n=n, slots=n, identity_hash=True, feeds_per_tick=2,
        feed_entries=16, announce_period=8, antientropy=2,
        tick_mode="r5", gossip_mode="pick", loss=0.1, lhm_max=8,
        suspicion_ticks=4,
    )
    ds = swim.init_state(dp, jax.random.PRNGKey(0))
    ps = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    ds = swim.set_degraded(ds, [5], loss=0.4, lag=1)
    ps = swim_pview.set_degraded(ps, [5], loss=0.4, lag=1)
    for i in range(12):
        key = jax.random.fold_in(jax.random.PRNGKey(7), i)
        if i == 5:
            ds = swim.set_alive(ds, 9, False)
            ps = swim_pview.set_alive(ps, 9, False)
        ds = swim.tick(ds, key, dp)
        ps = swim_pview.tick(ps, key, pp)
        assert jnp.array_equal(ds.events, ps.events), (
            i,
            dict(zip(KERNEL_EVENTS, np.asarray(ds.events))),
            dict(zip(KERNEL_EVENTS, np.asarray(ps.events))),
        )
        for f in ("lhm", "susp_conf", "susp_start", "probe_deadline",
                  "inc", "susp_subj", "susp_deadline"):
            assert jnp.array_equal(getattr(ds, f), getattr(ps, f)), (i, f)


# ---------------------------------------------------------------------------
# 4. the A/B: flaky member poisons vanilla, lifeguard collapses it
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kernel,n,kw", [
    ("dense", 64, {}),
    ("pview", 64, {"slots": 32, "feeds_per_tick": 2, "feed_entries": 16}),
])
def test_flaky_node_ab_false_positives_collapse(kernel, n, kw):
    """Seeded vanilla-vs-lifeguard regression on the scanned tick_n:
    >= 5x fewer ground-truth false-positive suspicions under one
    degraded (lagged) member, real-crash detection within 2x."""
    from corrosion_tpu.models.cluster import flaky_node_ab

    # r10 wall-budget trim: the ~22 s these replays each cost was ~all
    # XLA compile — two step programs (chunk=20 and detect_chunk=5) per
    # mode.  Aligning detect_chunk with chunk compiles ONE step shape
    # (≈11 s/test), and window 120→80 keeps every margin: v_fp 39 vs
    # the ≥15 floor, ≥5× collapse, detection parity at 20-tick
    # granularity.  Acceptance ratios below are unchanged.
    r = flaky_node_ab(
        kernel=kernel, seed=3, n=n, boot_ticks=20, window=80, lag=2,
        chunk=20, detect_chunk=20, **kw,
    )
    v, lf = r["vanilla"], r["lifeguard"]
    # the pathology must actually manifest in vanilla mode...
    assert v["suspect_fp"] >= 15, r
    # ...and collapse >= 5x under lifeguard
    assert v["suspect_fp"] >= 5 * max(1, lf["suspect_fp"]), r
    # wrongful downs collapse too
    assert v["down_fp"] >= 5 * max(1, lf["down_fp"]), r
    # the degraded member's own health score rose (LHA-Probe engaged)
    assert lf["lhm_degraded"] >= 1, r
    # a truly-crashed member is still detected, within 2x vanilla
    assert v["detect_ticks"] is not None and lf["detect_ticks"] is not None, r
    assert lf["detect_ticks"] <= 2 * v["detect_ticks"], r


# ---------------------------------------------------------------------------
# 5a. host Membership: LHM ramp/relax + suspicion windows
# ---------------------------------------------------------------------------


def _actor(i):
    from corrosion_tpu.types.actor import Actor, ActorId
    from corrosion_tpu.types.base import Timestamp

    return Actor(
        id=ActorId(bytes([i]) * 16), addr=f"node{i}",
        ts=Timestamp.from_unix(i),
    )


def test_host_lhm_ramps_on_self_suspicion_and_relaxes_on_ack():
    net = MemNetwork()
    ms = Membership(_actor(1), net.transport("node1"), LG_FAST,
                    rng=random.Random(1))
    assert ms.lhm_multiplier == 1.0
    # hearing ourselves suspected bumps LHM and refutes
    ms._apply_self_update(
        MemberUpdate(ms.identity, 0, MemberState.SUSPECT)
    )
    assert ms.lhm == 1 and ms.lhm_multiplier == 2.0
    assert ms._incarnation == 1  # refutation incarnation bump
    # a successful probe round relaxes it
    ms._lhm_relax()
    assert ms.lhm == 0 and ms.lhm_multiplier == 1.0
    # saturates at lhm_max
    for _ in range(LG_FAST.lhm_max + 5):
        ms._lhm_bump("test")
    assert ms.lhm == LG_FAST.lhm_max


def test_host_lhm_inert_with_lifeguard_off():
    net = MemNetwork()
    ms = Membership(_actor(1), net.transport("node1"), FAST,
                    rng=random.Random(1))
    ms._lhm_bump("test")
    assert ms.lhm == 0 and ms.lhm_multiplier == 1.0


def test_suspect_timeout_confirmed_curve():
    cfg = LG_FAST
    n = 16
    lo = cfg.suspect_timeout(n)
    hi = lo * cfg.susp_ceiling
    # one lone suspector: the full ceiling to refute
    assert cfg.suspect_timeout_confirmed(n, 1) == pytest.approx(hi)
    # monotone non-increasing in confirmers, floor at susp_k+1 total
    vals = [cfg.suspect_timeout_confirmed(n, c) for c in range(1, 7)]
    assert all(a >= b - 1e-9 for a, b in zip(vals, vals[1:]))
    assert vals[cfg.susp_k] == pytest.approx(lo)
    # lifeguard off: flat at the vanilla window
    off = SwimConfig(probe_period=cfg.probe_period,
                     suspicion_mult=cfg.suspicion_mult)
    assert off.suspect_timeout_confirmed(n, 1) == pytest.approx(
        off.suspect_timeout(n)
    )


def test_confirmer_set_grows_per_distinct_peer_only():
    net = MemNetwork()
    ms = Membership(_actor(1), net.transport("node1"), LG_FAST,
                    rng=random.Random(1))
    b, p1, p2 = _actor(2), _actor(3), _actor(4)
    ms._apply_update(MemberUpdate(b, 0, MemberState.ALIVE))
    ms._apply_update(MemberUpdate(b, 0, MemberState.SUSPECT), via=p1.id)
    m = ms.members[b.id]
    assert m.suspectors == {p1.id}
    # same peer re-asserting: no new independence
    ms._apply_update(MemberUpdate(b, 0, MemberState.SUSPECT), via=p1.id)
    assert m.suspectors == {p1.id}
    # a second peer confirms (equal precedence would NOT supersede —
    # the confirmer path must fire anyway)
    ms._apply_update(MemberUpdate(b, 0, MemberState.SUSPECT), via=p2.id)
    assert m.suspectors == {p1.id, p2.id}
    # refutation resets the epoch
    ms._apply_update(MemberUpdate(b, 1, MemberState.ALIVE), via=p1.id)
    assert ms.members[b.id].suspectors == set()


# ---------------------------------------------------------------------------
# 5b. buddy refutation end-to-end over MemNetwork
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_buddy_ping_prompts_immediate_refutation():
    """A (holding B as SUSPECT) pings B: the suspect update rides the
    ping itself, B refutes by incarnation bump without ever receiving
    the rumor from gossip."""
    from corrosion_tpu.runtime.tripwire import Tripwire

    net = MemNetwork(seed=3)
    a = mk_node(net, 1, cfg=LG_FAST)
    b = mk_node(net, 2, cfg=LG_FAST)
    trip = Tripwire()
    a.start(trip)
    b.start(trip)
    try:
        # A knows B and holds it SUSPECT at inc 0; B has no idea
        a._apply_update(MemberUpdate(b.identity, 0, MemberState.ALIVE))
        a._apply_update(
            MemberUpdate(b.identity, 0, MemberState.SUSPECT),
            via=a.identity.id,
        )
        assert a.members[b.identity.id].state == MemberState.SUSPECT
        # A's own probe loop delivers the buddy notification in-ping
        assert await wait_until(lambda: b._incarnation >= 1, timeout=5.0)
        # and the refutation clears A's suspicion (ack direct-evidence
        # path or the gossiped alive@1)
        assert await wait_until(
            lambda: (
                b.identity.id in a.members
                and a.members[b.identity.id].state == MemberState.ALIVE
            ),
            timeout=5.0,
        )
    finally:
        trip.trip()
        await a.stop()
        await b.stop()


# ---------------------------------------------------------------------------
# 5c. per-node fault knobs in net/mem.py
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_node_outbound_loss_is_asymmetric():
    net = MemNetwork(seed=1)
    got = {"a": 0, "b": 0}

    async def on_dg_a(src, data):
        got["a"] += 1

    async def on_dg_b(src, data):
        got["b"] += 1

    async def nop_uni(src, data):
        pass

    async def nop_bi(stream):
        stream.close()

    net.listener("a").serve(on_dg_a, nop_uni, nop_bi)
    net.listener("b").serve(on_dg_b, nop_uni, nop_bi)
    net.degrade("b", datagram_loss=1.0)
    ta, tb = net.transport("a"), net.transport("b")
    for _ in range(10):
        await ta.send_datagram("b", b"x")  # INBOUND to b: unaffected
        await tb.send_datagram("a", b"y")  # OUTBOUND from b: all lost
    await asyncio.sleep(0.05)
    assert got["b"] == 10 and got["a"] == 0
    net.restore("b")
    await tb.send_datagram("a", b"z")
    await asyncio.sleep(0.05)
    assert got["a"] == 1


@pytest.mark.asyncio
async def test_node_duplicate_delivers_twice():
    net = MemNetwork(seed=1, faults=LinkFaults(node_duplicate={"a": 1.0}))
    seen = []

    async def on_dg(src, data):
        seen.append(data)

    async def nop_uni(src, data):
        pass

    async def nop_bi(stream):
        stream.close()

    net.listener("b").serve(on_dg, nop_uni, nop_bi)
    await net.transport("a").send_datagram("b", b"dup")
    await asyncio.sleep(0.05)
    assert seen == [b"dup", b"dup"]


@pytest.mark.asyncio
async def test_node_latency_slows_only_that_sender():
    import time as _time

    net = MemNetwork(seed=1, faults=LinkFaults(node_latency={"a": 0.15}))
    stamps = {}

    async def on_dg(src, data):
        stamps[src] = _time.monotonic()

    async def nop_uni(src, data):
        pass

    async def nop_bi(stream):
        stream.close()

    net.listener("c").serve(on_dg, nop_uni, nop_bi)
    t0 = _time.monotonic()
    await net.transport("a").send_datagram("c", b"slow")
    await net.transport("b").send_datagram("c", b"fast")
    assert await wait_until(lambda: len(stamps) == 2, timeout=2.0)
    assert stamps["a"] - t0 >= 0.14
    assert stamps["b"] - t0 < 0.1

"""Real multi-process DCN mesh test (VERDICT r3 item 4).

Two OS processes, each with 4 virtual CPU devices, joined by
jax.distributed over localhost — the smallest genuine instance of the
multi-host story in `parallel/mesh.py:multihost_member_mesh` (host axis
outermost, member blocks process-contiguous). Unlike the degenerate
single-process case, the per-tick gossip collectives here really cross a
process boundary (gRPC standing in for DCN).

Parity bar: both workers print identical replicated stats/fingerprint
lines, and those match a single-process flat-mesh run of the same
computation — the mesh layout and the transport are not allowed to
change a single bit of protocol state.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.ops import swim
from corrosion_tpu.parallel import member_mesh, shard_member_state, sharded_tick
from corrosion_tpu.runtime import jaxenv

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dcn_worker.py")
N_TICKS = 5


from tests.test_agent import free_port as _free_port  # noqa: E402


def _run_workers(nprocs: int, local_devices: int) -> list:
    coord = f"127.0.0.1:{_free_port()}"
    env = jaxenv.stripped_env(n_devices=local_devices)
    # each worker builds its own CPU client; the coordinator handshake
    # must happen before any backend init, which the worker script
    # guarantees by initializing distributed first
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", WORKER, coord, str(pid), str(nprocs),
             str(N_TICKS), str(local_devices)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # one worker failing must not leak siblings blocked in
        # jax.distributed collectives for the rest of the pytest run
        for q in procs:
            if q.poll() is None:
                q.kill()
    return outs


def test_two_process_mesh_parity():
    outs = _run_workers(nprocs=2, local_devices=4)

    # both workers observed the same replicated cluster state
    a, b = outs
    assert a["fingerprint"] == b["fingerprint"]
    assert a["stats"] == b["stats"]

    # ... and it matches the single-process flat-mesh computation
    fp, stats = _flat_reference(n_dev=8)
    assert a["fingerprint"] == fp
    assert a["stats"] == stats


def _flat_reference(n_dev: int):
    """The single-process flat-mesh run every decomposition must match."""
    devices = jax.devices()[:n_dev]
    params = swim.SwimParams(n=8 * n_dev)
    mesh = member_mesh(devices)
    state = shard_member_state(
        swim.init_state(params, jax.random.PRNGKey(3)), mesh
    )
    tick = sharded_tick(params, mesh)
    rng = jax.random.PRNGKey(9)
    for _ in range(N_TICKS):
        rng, key = jax.random.split(rng)
        state = tick(state, key)
    stats = {k: float(v) for k, v in swim.membership_stats(state).items()}
    fp = int(jnp.sum((state.view.astype(jnp.int32) * 92821) % 1000003))
    return fp, stats


@pytest.mark.slow
def test_four_process_mesh_parity():
    """Wider host axis: 4 processes x 2 devices — the same 8-device,
    64-member job as the 2x4 case, so the [hosts, members] layout must
    reproduce the identical fingerprint across a different process
    decomposition (mesh layout never changes protocol state)."""
    outs = _run_workers(nprocs=4, local_devices=2)
    fps = {o["fingerprint"] for o in outs}
    assert len(fps) == 1
    assert all(o["stats"] == outs[0]["stats"] for o in outs)
    fp, stats = _flat_reference(n_dev=8)
    assert outs[0]["fingerprint"] == fp
    assert outs[0]["stats"] == stats

"""Snapshot-plane unit tests (r17 catch-up round): container codec,
schema-sha gate, build/install roundtrip through the locked-swap path,
the version-gated SnapshotReq peer op, cache staleness, and the digest
`heads_total` trailing-field tolerance.

All sqlite work is tiny-shape file dbs (tmp_path) — the e2e agent-level
scenarios live in test_sync_resume.py."""

import os
import sqlite3
import zlib

import pytest

from corrosion_tpu.store import snapshot as snap
from corrosion_tpu.store.bookkeeping import Bookie
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.store.schema import parse_sql
from corrosion_tpu.types.base import Timestamp

SCHEMA = "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"

# clock-table parity EXCLUDES the ts column: it is origin-local
# bookkeeping (a replica applying remote changes stores ts=0 on the
# standing delta path), so it legitimately differs by route; the CRDT
# merge state is the other six columns
CLOCK_SQL = (
    "SELECT pk, cid, col_version, db_version, seq, site_id"
    " FROM tests__crdt_clock ORDER BY pk, cid, db_version"
)


def seeded_store(path, n_versions=12, schema=SCHEMA):
    store = CrdtStore(str(path))
    store.apply_schema_sql(schema)
    for i in range(n_versions):
        with store.write_tx(Timestamp.now()) as tx:
            tx.execute(
                "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                (i, f"v{i}"),
            )
    return store


def store_bookie(store) -> Bookie:
    bookie = Bookie()
    for aid in store.booked_actor_ids():
        bookie.insert(aid, store.load_booked_versions(aid))
    return bookie


def build(store, out_path, chunk_bytes=4096):
    return snap.build_snapshot_file(
        store.path,
        str(out_path),
        store.schema,
        store.site_id.bytes16,
        snap.bookie_watermark(store_bookie(store)),
        chunk_bytes=chunk_bytes,
    )


# -- codec ------------------------------------------------------------------


def test_header_codec_roundtrip():
    h = snap.SnapshotHeader(
        schema_sha=b"\xab" * 32,
        site_id=b"\x07" * 16,
        wall=123.5,
        raw_bytes=1 << 30,
        chunk_bytes=65536,
        watermark={b"\x01" * 16: [(1, 10), (12, 99)], b"\x02" * 16: [(5, 5)]},
    )
    h2 = snap.decode_header(snap.encode_header(h))
    assert h2 == h
    assert h2.watermark_total() == 10 + 88 + 1


def test_snapshot_msg_codec_roundtrip():
    h = snap.SnapshotHeader(
        schema_sha=b"\x01" * 32, site_id=b"\x02" * 16, wall=1.0,
        raw_bytes=10, chunk_bytes=4,
    )
    assert snap.decode_snapshot_msg(snap.encode_snapshot_msg_header(h)) == h
    z = zlib.compress(b"hello world")
    assert snap.decode_snapshot_msg(snap.encode_snapshot_msg_chunk(z)) == z
    d = snap.SnapshotDone(3, 100, 42)
    assert snap.decode_snapshot_msg(snap.encode_snapshot_msg_done(d)) == d
    assert (
        snap.decode_snapshot_msg(
            snap.encode_snapshot_msg_rejection(snap.REJECT_SCHEMA)
        )
        == snap.REJECT_SCHEMA
    )


def test_schema_sha_canonical_and_gated():
    a = parse_sql(SCHEMA)
    b = parse_sql(
        "create   table tests (id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT)  ;"
    )
    # whitespace/case-insensitive canonicalization... but sqlite keeps
    # the raw DDL, so normalization is what makes these agree
    assert snap.schema_sha(a) == snap.schema_sha(b)
    c = parse_sql(SCHEMA + "\nCREATE TABLE more (id INTEGER PRIMARY KEY);")
    assert snap.schema_sha(a) != snap.schema_sha(c)
    # runtime-owned tables (the SLO canary) are excludable from the gate
    assert snap.schema_sha(c, exclude=("more",)) == snap.schema_sha(a)


def test_bi_payload_snapshot_req_version_gate():
    from corrosion_tpu.types.actor import ActorId, ClusterId
    from corrosion_tpu.types.codec import (
        SnapshotReq,
        decode_bi_payload,
        decode_bi_payload_any,
        encode_bi_payload_snapshot_req,
        encode_bi_payload_sync_start,
    )

    req = SnapshotReq(
        actor_id=ActorId(b"\x09" * 16),
        schema_sha=b"\x11" * 32,
        cluster_id=ClusterId(3),
    )
    data = encode_bi_payload_snapshot_req(req)
    kind, decoded = decode_bi_payload_any(data)
    assert kind == "snapshot" and decoded == req
    # the version gate: a pre-r17 decoder refuses the new op outright
    # (its serve path maps ValueError to a counted, closed session)
    with pytest.raises(ValueError):
        decode_bi_payload(data)
    # and the dispatching decoder keeps parsing old SyncStart frames
    start = encode_bi_payload_sync_start(ActorId(b"\x01" * 16))
    kind, payload = decode_bi_payload_any(start)
    assert kind == "sync" and payload[0] == ActorId(b"\x01" * 16)


def test_digest_heads_total_rides_and_tolerates_eof():
    from corrosion_tpu.runtime.digest import (
        NodeDigest,
        decode_digest,
        encode_digest,
    )
    from corrosion_tpu.types.codec import Writer

    d = NodeDigest(
        actor_id=b"\x05" * 16, seq=3, wall=10.0, view_hash=7, view_size=2,
        heads_total=12345,
    )
    enc = encode_digest(d)
    assert decode_digest(enc).heads_total == 12345
    # a pre-r17 encoder never writes the trailing fields: strip exactly
    # the trailing uvarint(12345) PLUS the r20 empty-alert-block count
    # and the r23 empty-hotspot-block count (uvarint(0), one byte each)
    # that now follow it, and the decoder must default all three
    # (heads_total=0, alerts=[], hotspots=[])
    w = Writer()
    w.uvarint(12345)
    old_bytes = enc[: -(len(w.bytes()) + 2)]
    old = decode_digest(old_bytes)
    assert old.heads_total == 0 and old.alerts == []
    assert old.hotspots == []
    # an r20-era encoder wrote heads_total + alerts but no hotspot
    # block: strip only the final count byte and hotspots must default
    # while the older trailing fields still decode
    mid = decode_digest(enc[:-1])
    assert mid.heads_total == 12345 and mid.hotspots == []


# -- build + install --------------------------------------------------------


def test_build_install_roundtrip_preserves_state(tmp_path):
    a = seeded_store(tmp_path / "a.db")
    out = tmp_path / "a.snapshot"
    header = build(a, out)
    assert header.raw_bytes > 0
    assert header.watermark_total() == 12
    assert header.schema_sha == snap.schema_sha(a.schema)

    b = CrdtStore(str(tmp_path / "b.db"))
    b.apply_schema_sql(SCHEMA)
    b_site = b.site_id
    with b.swapped_database():
        res = snap.install_snapshot_file(
            str(out), b.path,
            expect_schema_sha=snap.schema_sha(b.schema),
            self_site_id=b_site.bytes16,
        )
    assert res.watermark_versions == 12

    # user rows + CRDT merge state identical; identity preserved;
    # per-node member state scrubbed (backup-plane contract)
    rows_a = a._conn.execute("SELECT * FROM tests ORDER BY id").fetchall()
    rows_b = b._conn.execute("SELECT * FROM tests ORDER BY id").fetchall()
    assert [tuple(r) for r in rows_a] == [tuple(r) for r in rows_b]
    ca = [tuple(r) for r in a._conn.execute(CLOCK_SQL)]
    cb = [tuple(r) for r in b._conn.execute(CLOCK_SQL)]
    assert ca == cb and len(ca) > 0
    assert b.site_id == b_site
    row = b._conn.execute("SELECT site_id FROM __crdt_site").fetchone()
    assert bytes(row["site_id"]) == b_site.bytes16
    assert (
        b._conn.execute("SELECT COUNT(*) FROM __corro_members").fetchone()[0]
        == 0
    )
    # the installed store keeps writing: post-swap tx gets the next
    # version for b's OWN site, not the builder's
    with b.write_tx(Timestamp.now()) as tx:
        tx.execute(
            "INSERT OR REPLACE INTO tests (id, text) VALUES (999, 'post')"
        )
    assert b.db_version_for(b_site) == 1
    a.close()
    b.close()


def test_install_refuses_schema_mismatch(tmp_path):
    a = seeded_store(tmp_path / "a.db", n_versions=3)
    out = tmp_path / "a.snapshot"
    build(a, out)
    c = CrdtStore(str(tmp_path / "c.db"))
    c.apply_schema_sql(
        "CREATE TABLE other (id INTEGER NOT NULL PRIMARY KEY, v TEXT);"
    )
    before = sqlite3.connect(c.path).execute(
        "SELECT COUNT(*) FROM sqlite_master"
    ).fetchone()[0]
    with pytest.raises(snap.SnapshotSchemaMismatch):
        snap.install_snapshot_file(
            str(out), c.path,
            expect_schema_sha=snap.schema_sha(c.schema),
            self_site_id=c.site_id.bytes16,
        )
    # refused BEFORE the swap: the target database is untouched
    after = sqlite3.connect(c.path).execute(
        "SELECT COUNT(*) FROM sqlite_master"
    ).fetchone()[0]
    assert after == before
    a.close()
    c.close()


def test_torn_snapshot_detected(tmp_path):
    a = seeded_store(tmp_path / "a.db", n_versions=3)
    out = tmp_path / "a.snapshot"
    build(a, out, chunk_bytes=1024)
    data = open(out, "rb").read()
    torn = tmp_path / "torn.snapshot"
    torn.write_bytes(data[: len(data) // 2])
    with pytest.raises(snap.SnapshotError):
        snap.decompress_snapshot_file(str(torn), str(tmp_path / "x.db"))
    a.close()


def test_watermark_excludes_gaps_and_incomplete_partials():
    from corrosion_tpu.store.bookkeeping import (
        NULL_GAP_STORE,
        PartialVersion,
    )
    from corrosion_tpu.types.actor import ActorId
    from corrosion_tpu.types.rangeset import RangeSet

    origin = ActorId(b"\x03" * 16)
    bookie = Bookie()
    with bookie.ensure(origin).write() as bv:
        s = bv.snapshot()
        s.insert_db(NULL_GAP_STORE, RangeSet([(1, 4), (8, 10)]))
        bv.commit_snapshot(s)
        bv.insert_partial(
            9,
            PartialVersion(seqs=RangeSet([(0, 2)]), last_seq=9,
                           ts=Timestamp(1)),
        )
    wm = snap.bookie_watermark(bookie)
    assert wm == {origin.bytes16: [(1, 4), (8, 8), (10, 10)]}


def test_local_covered_guard_own_origin_only():
    """The install guard refuses only when versions WE originated are
    missing from the watermark (irreplaceable); remote-origin overhang
    is re-fetchable via the top-up and must not block a live-fire
    bootstrap."""
    from types import SimpleNamespace

    from corrosion_tpu.agent.catchup import _local_covered_by
    from corrosion_tpu.store.bookkeeping import NULL_GAP_STORE
    from corrosion_tpu.types.actor import ActorId
    from corrosion_tpu.types.rangeset import RangeSet

    me = ActorId(b"\x01" * 16)
    other = ActorId(b"\x02" * 16)
    bookie = Bookie()
    for who, upto in ((me, 3), (other, 50)):
        with bookie.ensure(who).write() as bv:
            s = bv.snapshot()
            s.insert_db(NULL_GAP_STORE, RangeSet([(1, upto)]))
            bv.commit_snapshot(s)
    agent = SimpleNamespace(bookie=bookie, actor_id=me)
    covered = snap.SnapshotHeader(
        schema_sha=b"", site_id=other.bytes16, wall=0.0, raw_bytes=0,
        chunk_bytes=1,
        # our 3 own versions covered; `other`'s watermark STALE (40<50)
        watermark={me.bytes16: [(1, 3)], other.bytes16: [(1, 40)]},
    )
    assert _local_covered_by(agent, covered) is True
    uncovered = snap.SnapshotHeader(
        schema_sha=b"", site_id=other.bytes16, wall=0.0, raw_bytes=0,
        chunk_bytes=1,
        watermark={me.bytes16: [(1, 2)], other.bytes16: [(1, 50)]},
    )
    assert _local_covered_by(agent, uncovered) is False


def test_cache_staleness_window(tmp_path):
    a = seeded_store(tmp_path / "a.db", n_versions=3)
    cache = snap.SnapshotCache(a.path)
    bookie = store_bookie(a)
    h1 = cache.ensure_fresh(a.schema, a.site_id.bytes16, bookie, 60.0)
    built1 = cache.built_mono
    # within the window: the SAME build serves every requester
    h2 = cache.ensure_fresh(a.schema, a.site_id.bytes16, bookie, 60.0)
    assert h2 is h1 and cache.built_mono == built1
    # past the window: rebuilt
    cache.built_mono -= 120.0
    h3 = cache.ensure_fresh(a.schema, a.site_id.bytes16, bookie, 60.0)
    assert h3 is not h1 and cache.built_mono != built1
    cache.drop()
    assert not os.path.exists(cache.path)
    a.close()

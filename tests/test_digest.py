"""r12 telemetry-digest wire codec + observatory units.

Property tests for the sparse histogram codec (digest → bytes → digest
identical; merge-of-decoded ≡ decode-of-merged), the full NodeDigest
roundtrip over randomized field content, the canonical view hash, the
freshest-per-node adoption rule, the budgeted ext picker, and the
divergence episode state machine on fabricated digests — the unit half
of what tests/test_cluster_obs.py exercises live.
"""

from __future__ import annotations

import random
import time

import pytest

from corrosion_tpu.runtime import latency as lat
from corrosion_tpu.runtime.digest import (
    NodeDigest,
    decode_digest,
    encode_digest,
    merge_stage_hists,
    read_hist,
    view_hash,
    write_hist,
)
from corrosion_tpu.types.codec import Reader, Writer


def _rand_hist(rng, n_samples=200, scale=2.0):
    h = lat.LatencyHistogram()
    for _ in range(rng.randrange(n_samples)):
        h.observe(rng.lognormvariate(-6.0, scale))
    return h


def _rand_digest(rng, seq=1):
    stages = {
        s: _rand_hist(rng)
        for s in lat.E2E_STAGES
        if rng.random() < 0.8
    }
    return NodeDigest(
        actor_id=rng.randbytes(16),
        seq=seq,
        wall=time.time() + rng.uniform(-5, 5),
        view_hash=rng.getrandbits(64),
        view_size=rng.randrange(1, 1000),
        alive=rng.randrange(1000),
        suspect=rng.randrange(50),
        downed=rng.randrange(50),
        lhm=rng.randrange(9),
        loop_lag=rng.random(),
        sync_backlog={
            rng.randbytes(16): rng.randrange(1, 1 << 40)
            for _ in range(rng.randrange(4))
        },
        events={
            f"ev_{i}": rng.randrange(1 << 32)
            for i in range(rng.randrange(6))
        },
        stages=stages,
    )


def test_hist_codec_roundtrip_identical():
    rng = random.Random(1)
    for _ in range(50):
        h = _rand_hist(rng, scale=rng.uniform(0.5, 4.0))
        w = Writer()
        write_hist(w, h)
        out = read_hist(Reader(w.bytes()))
        assert out.nonzero_buckets() == h.nonzero_buckets()
        assert out.count == h.count
        assert out.total == pytest.approx(h.total)
        for q in lat.QUANTILES:
            assert out.quantile(q) == h.quantile(q)


def test_hist_codec_merge_of_decoded_equals_decode_of_merged():
    rng = random.Random(2)
    for _ in range(25):
        a, b = _rand_hist(rng), _rand_hist(rng)
        wa, wb = Writer(), Writer()
        write_hist(wa, a)
        write_hist(wb, b)
        merged_then = a.copy().merge(b)
        decoded_then = read_hist(Reader(wa.bytes())).merge(
            read_hist(Reader(wb.bytes()))
        )
        wm = Writer()
        write_hist(wm, merged_then)
        decode_of_merged = read_hist(Reader(wm.bytes()))
        assert (
            decoded_then.nonzero_buckets()
            == decode_of_merged.nonzero_buckets()
            == merged_then.nonzero_buckets()
        )
        assert decoded_then.total == pytest.approx(decode_of_merged.total)


def test_digest_roundtrip_randomized():
    rng = random.Random(3)
    for trial in range(40):
        d = _rand_digest(rng, seq=trial)
        out = decode_digest(encode_digest(d))
        assert out.actor_id == d.actor_id
        assert out.seq == d.seq
        assert out.wall == pytest.approx(d.wall)
        assert out.view_hash == d.view_hash
        assert out.view_size == d.view_size
        assert (out.alive, out.suspect, out.downed) == (
            d.alive, d.suspect, d.downed,
        )
        assert out.lhm == d.lhm
        assert out.loop_lag == pytest.approx(d.loop_lag)
        assert out.sync_backlog == d.sync_backlog
        assert out.events == d.events
        # only non-empty histograms travel
        want = {s for s, h in d.stages.items() if h.count > 0}
        assert set(out.stages) == want
        for s in want:
            assert (
                out.stages[s].nonzero_buckets()
                == d.stages[s].nonzero_buckets()
            )


def test_digest_decode_rejects_garbage_and_wrong_version():
    with pytest.raises(Exception):
        decode_digest(b"")
    rng = random.Random(4)
    good = encode_digest(_rand_digest(rng))
    with pytest.raises(ValueError):
        decode_digest(b"\x63" + good[1:])  # future major version
    with pytest.raises(Exception):
        decode_digest(good[: len(good) // 2])  # truncated


def test_view_hash_canonical_and_discriminating():
    ids = [bytes([i]) * 16 for i in range(5)]
    rng = random.Random(5)
    shuffled = list(ids)
    rng.shuffle(shuffled)
    assert view_hash(ids) == view_hash(shuffled)  # order-free
    assert view_hash(ids) != view_hash(ids[:-1])  # set-sensitive
    assert view_hash([]) != view_hash(ids)
    with pytest.raises(ValueError):
        view_hash([b"\x01" * 15])


def test_merge_stage_hists_exact_across_digests():
    rng = random.Random(6)
    a, b = _rand_digest(rng), _rand_digest(rng)
    merged = merge_stage_hists([a, b])
    for s in lat.E2E_STAGES:
        want = lat.LatencyHistogram()
        for d in (a, b):
            if s in d.stages:
                want.merge(d.stages[s])
        assert merged[s].nonzero_buckets() == want.nonzero_buckets()


# -- observatory units (fabricated agents, no network) ----------------------


class _FakeMembership:
    def __init__(self):
        from corrosion_tpu.agent.membership import SwimConfig

        self.members = {}
        self.downed = {}
        self.config = SwimConfig()
        self.lhm = 0

    @property
    def cluster_size(self):
        return 1 + len(self.members)


class _FakeBookie:
    def items(self):
        return {}


class _FakeAgent:
    def __init__(self, name: bytes):
        from corrosion_tpu.runtime.config import Config
        from corrosion_tpu.types.actor import Actor, ActorId

        self.config = Config()
        self.actor = Actor(id=ActorId(name), addr="fake")
        self.membership = _FakeMembership()
        self.bookie = _FakeBookie()

    @property
    def actor_id(self):
        return self.actor.id


def _mk_obs(name=b"\x01" * 16):
    from corrosion_tpu.agent.observatory import Observatory

    return Observatory(_FakeAgent(name))


def _held_digest(obs, actor_id: bytes, seq=1, wall=None, vh=0):
    d = NodeDigest(
        actor_id=actor_id,
        seq=seq,
        wall=wall if wall is not None else time.time(),
        view_hash=vh,
        view_size=1,
    )
    return obs.receive(encode_digest(d))


def test_observatory_freshest_per_node_wins():
    obs = _mk_obs()
    other = b"\x02" * 16
    assert _held_digest(obs, other, seq=5, wall=100.0) is not None
    # older wall → dropped
    assert _held_digest(obs, other, seq=9, wall=50.0) is None
    assert obs._store[other].digest.seq == 5
    # newer wall → adopted
    assert _held_digest(obs, other, seq=6, wall=200.0) is not None
    assert obs._store[other].digest.seq == 6
    # our own digest relayed back → ignored
    assert _held_digest(obs, b"\x01" * 16, seq=99, wall=1e12) is None


def test_observatory_pick_ext_budget_and_rotation():
    obs = _mk_obs()
    _held_digest(obs, b"\x02" * 16, seq=1)
    _held_digest(obs, b"\x03" * 16, seq=1)
    seen = set()
    # both digests fit a generous budget; rotation must alternate
    for _ in range(4):
        ext = obs.pick_ext(10_000)
        assert ext is not None
        seen.add(decode_digest(ext).actor_id)
    assert seen == {b"\x02" * 16, b"\x03" * 16}
    # a hopeless budget yields nothing (and counts the skip)
    assert obs.pick_ext(4) is None
    # sends_left exhausts: transmissions are bounded per adoption
    total = 0
    while obs.pick_ext(10_000) is not None:
        total += 1
        assert total < 100, "sends never exhausted"
    assert total > 0


def test_observatory_divergence_episode_state_machine(tmp_path, monkeypatch):
    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    from corrosion_tpu.agent.membership import MemberState, _Member
    from corrosion_tpu.types.actor import Actor, ActorId

    obs = _mk_obs()
    obs.cfg.divergence_checks = 2
    obs.cfg.digest_interval_secs = 10.0  # silence never fires here
    peer = b"\x02" * 16
    obs.agent.membership.members[ActorId(peer)] = _Member(
        actor=Actor(id=ActorId(peer), addr="peer"),
        state=MemberState.ALIVE,
    )
    my_hash = view_hash([b"\x01" * 16, peer])

    # agreeing view → clean
    _held_digest(obs, peer, seq=1, vh=my_hash)
    r = obs.check_divergence()
    assert not r["divergent"] and r["groups"] == 1

    # conflicting view hash → divergent, episode opens on the SECOND
    # consecutive check, exactly one incident + episode
    _held_digest(obs, peer, seq=2, vh=my_hash ^ 0xDEAD)
    r1 = obs.check_divergence()
    assert r1["divergent"] and not r1["episode_open"]
    r2 = obs.check_divergence()
    assert r2["episode_open"] and r2["episodes"] == 1
    obs.check_divergence()
    assert obs._episodes == 1  # still the same episode
    dumps = list(tmp_path.glob("*cluster_divergence*"))
    assert len(dumps) == 1

    # agreement again: hysteresis holds the episode for one clean
    # check, the second closes it; a NEW divergence is a NEW episode
    _held_digest(obs, peer, seq=3, vh=my_hash)
    assert obs.check_divergence()["episode_open"]
    assert not obs.check_divergence()["episode_open"]
    _held_digest(obs, peer, seq=4, vh=my_hash ^ 0xBEEF)
    obs.check_divergence()
    assert obs.check_divergence()["episodes"] == 2
    assert len(list(tmp_path.glob("*cluster_divergence*"))) == 2

    # disarm freezes the state machine (planned teardown)
    _held_digest(obs, peer, seq=5, vh=my_hash)
    obs.disarm()
    obs.check_divergence()
    obs.check_divergence()
    assert obs._episode_open  # frozen open, no bonus episode
    assert obs._episodes == 2


def test_observatory_silence_requires_prior_report(monkeypatch):
    """An ACTIVE member that has NEVER sent a digest is not 'silent'
    (boot grace); one that reported and stopped is."""
    from corrosion_tpu.agent.membership import MemberState, _Member
    from corrosion_tpu.types.actor import Actor, ActorId

    obs = _mk_obs()
    obs.cfg.divergence_checks = 1
    obs.cfg.digest_interval_secs = 0.01  # silent_after = 25 ms
    peer = b"\x02" * 16
    obs.agent.membership.members[ActorId(peer)] = _Member(
        actor=Actor(id=ActorId(peer), addr="peer"),
        state=MemberState.ALIVE,
    )
    assert not obs.check_divergence()["divergent"]  # never reported
    my_hash = view_hash([b"\x01" * 16, peer])
    _held_digest(obs, peer, seq=1, vh=my_hash)
    assert not obs.check_divergence()["divergent"]  # fresh
    time.sleep(0.05)
    r = obs.check_divergence()
    assert r["divergent"] and r["silent"]  # went silent
    # ... but not when the local loop itself was late (lag suppression)
    obs._self_lagged = True
    assert not obs.check_divergence()["silent"]


def test_observatory_oversize_digest_degrades_to_fit(monkeypatch):
    """r22: the encoded digest must FIT the gossip frame or pick_ext
    skips it on EVERY datagram and the split-brain signal starves
    cluster-wide — and because an open divergence episode adds an alert
    block to every node's digest, the overflow is self-sustaining.
    Once the cumulative histograms cross `max_wire_bytes`,
    build_and_store sheds the non-total stages (then events + the alert
    tail), but never the view/census core."""
    obs = _mk_obs()
    cap = obs.cfg.max_wire_bytes
    rng = random.Random(7)
    fat = {}
    for st in lat.E2E_STAGES:
        h = lat.LatencyHistogram()
        for _ in range(600):
            h.observe(rng.lognormvariate(-6.0, 4.0))
        fat[st] = h
    d = NodeDigest(
        actor_id=b"\x01" * 16,
        seq=1,
        wall=time.time(),
        view_hash=1234,
        view_size=4,
        alive=4,
        heads_total=755,
        alerts=[
            {"rule": "view-divergence", "severity": "page",
             "state": "firing", "since": 1.0, "value": 1.0}
        ],
        stages=fat,
    )
    assert len(encode_digest(d)) > cap  # the pathological input
    monkeypatch.setattr(obs, "snapshot_local", lambda: d)
    obs.build_and_store()
    enc = obs._store[b"\x01" * 16].encoded
    assert len(enc) <= cap, f"degrade left {len(enc)}B > {cap}B"
    got = decode_digest(enc)
    # the core the divergence detector feeds on is intact
    assert got.view_hash == 1234 and got.view_size == 4
    assert got.heads_total == 755
    assert set(got.stages) <= {"total"}
    # and a quiet SWIM frame's leftover budget now carries it
    assert obs.pick_ext(cap + 64) is not None

"""Serving-plane asymptote tests (r16): subscribe-time query dedupe with
refcounted matcher lifecycle, coalesced fan-out writes, laggard-shedding
backpressure, and stream admission control.

The failure discipline under test is Prime CCL (arXiv:2505.14065): a
slow consumer must DEGRADE — be shed with a typed terminal frame —
never stall the DiffExecutor or its sibling streams.  The banked
SUBS_SCALE.json ladder (scripts/bench_pubsub.py --scale) is guarded in
tests/test_subs_bank.py; everything here is tiny-shape and live.
"""

import asyncio

import pytest

from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.pubsub.fanout import StreamSink, SubLagging

from tests.test_agent import insert, wait_until
from tests.test_http_api import boot_with_api
from tests.test_pubsub_http import next_of


async def _shutdown(agent, api, *clients):
    for c in clients:
        await c.close()
    await api.stop()
    from corrosion_tpu.agent.run import shutdown

    await shutdown(agent)


class _RecordingSink(StreamSink):
    """Always-writable in-process sink: records delivered bytes."""

    def __init__(self, max_lag_bytes=1 << 20, max_lag_batches=1024):
        super().__init__(max_lag_bytes, max_lag_batches)
        self.received = bytearray()

    def writable(self):
        return True

    def write_some(self, data):
        self.received += data
        return len(data)

    def lines(self):
        return [l for l in bytes(self.received).split(b"\n") if l]


class _StalledSink(_RecordingSink):
    """Never-writable sink: the deterministic laggard."""

    def writable(self):
        return False


def _peek(name):
    from corrosion_tpu.runtime.metrics import METRICS

    for _kind, sname, _labels, value in METRICS.snapshot():
        if sname == name and not _labels:
            return value
    return 0.0


# -- dedupe + refcounted lifecycle ----------------------------------------


def test_dedupe_canonical_hash_shares_one_matcher():
    """Streams subscribing textual variants of one query (whitespace,
    comments) share ONE matcher: the canonical token-normalized hash
    dedupes at subscribe time, so k distinct queries — not N streams —
    bound the matcher count."""

    async def main():
        net = MemNetwork(seed=61)
        a, api, client = await boot_with_api(net, "agent-dedupe")
        try:
            await insert(a, 1, "pre")
            variants = [
                "SELECT id, text FROM tests",
                "SELECT id,  text   FROM tests",
                "SELECT id, text /* same */ FROM tests",
            ]
            its = []
            for v in variants:
                it = client.subscribe(v, skip_rows=True).__aiter__()
                await next_of(it, "eoq")
                its.append(it)
            assert len(api.subs.handles()) == 1, (
                "textual variants must dedupe onto one matcher"
            )
            assert _peek("corro.subs.dedupe.hits.total") >= 2
            assert api.subs.stream_count() == 3
            await insert(a, 2, "live")
            for it in its:
                ev = await next_of(it, "change")
                assert ev["change"][2] == [2, "live"]
        finally:
            await _shutdown(a, api, client)

    asyncio.run(main())


def test_matcher_linger_teardown_on_last_detach(tmp_path):
    """Refcounted lifecycle: the last stream's detach arms the linger
    timer; past the window the matcher and its sub db are reaped.  A
    re-subscribe INSIDE the window cancels the reaper and reuses the
    warm matcher (same query id)."""

    async def main():
        net = MemNetwork(seed=62)
        a, api, client = await boot_with_api(net, "agent-linger")
        # generous window for the reuse phase (a loaded 1-core host must
        # not reap before the quick re-subscribe lands); shrunk before
        # the teardown phase below
        a.config.subs.matcher_linger_secs = 5.0  # manager shares the object
        try:
            s1 = client.subscribe("SELECT text FROM tests", skip_rows=True)
            it = s1.__aiter__()
            await next_of(it, "eoq")
            qid = s1.query_id
            assert len(api.subs.handles()) == 1

            # re-subscribe inside the window keeps the matcher: close
            # the first stream, reattach before the linger fires
            await it.aclose()
            s2 = client.subscribe("SELECT text FROM tests", skip_rows=True)
            it2 = s2.__aiter__()
            await next_of(it2, "eoq")
            assert s2.query_id == qid, "warm matcher must be reused"

            # now drop the last stream and outwait a SHORT linger
            a.config.subs.matcher_linger_secs = 0.3
            await it2.aclose()
            assert await wait_until(
                lambda: len(api.subs.handles()) == 0, timeout=15.0
            ), "last detach must reap the matcher after the linger window"

            # a later subscribe builds a FRESH matcher
            s3 = client.subscribe("SELECT text FROM tests", skip_rows=True)
            it3 = s3.__aiter__()
            await next_of(it3, "eoq")
            assert s3.query_id != qid
        finally:
            await _shutdown(a, api, client)

    asyncio.run(main())


# -- admission control ----------------------------------------------------


def test_admission_rejects_past_max_streams():
    """[subs] max_streams: the N+1th stream gets a typed 503 (code
    subs_admission) and the rejection is counted; detaching a stream
    frees the slot."""

    async def main():
        net = MemNetwork(seed=63)
        a, api, client = await boot_with_api(net, "agent-admit")
        a.config.subs.max_streams = 2
        try:
            from corrosion_tpu.client import ClientError

            its = []
            for _ in range(2):
                it = client.subscribe(
                    "SELECT id, text FROM tests", skip_rows=True
                ).__aiter__()
                await next_of(it, "eoq")
                its.append(it)
            assert api.subs.stream_count() == 2

            rejected = _peek("corro.subs.admission.rejected.total")
            with pytest.raises(ClientError) as exc:
                it3 = client.subscribe(
                    "SELECT id, text FROM tests", skip_rows=True
                ).__aiter__()
                await next_of(it3, "eoq")
            assert exc.value.status == 503
            assert "subs_admission" in str(exc.value.body)
            assert _peek("corro.subs.admission.rejected.total") > rejected

            # freeing a slot re-admits
            await its.pop().aclose()
            assert await wait_until(
                lambda: api.subs.stream_count() == 1
            )
            it4 = client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True
            ).__aiter__()
            await next_of(it4, "eoq")
        finally:
            await _shutdown(a, api, client)

    asyncio.run(main())


# -- laggard shedding ------------------------------------------------------


def test_stalled_sink_is_shed_siblings_and_executor_unaffected():
    """THE laggard-shed pin: one stream whose transport never drains is
    shed with a SubLagging terminal once past its lag bounds, while (a)
    a sibling sink on the SAME matcher keeps receiving every event and
    (b) the DiffExecutor keeps producing diffs — events written AFTER
    the shed still reach the sibling.  Deterministic: the laggard is an
    in-process sink whose writable() is False, so no TCP buffering can
    blur the bound."""

    async def main():
        net = MemNetwork(seed=64)
        a, api, client = await boot_with_api(net, "agent-shed")
        try:
            handle, _ = await api.subs.get_or_insert(
                "SELECT id, text FROM tests"
            )
            healthy = _RecordingSink()
            stalled = _StalledSink(max_lag_bytes=2048, max_lag_batches=4)
            handle.attach_sink(healthy)
            handle.attach_sink(stalled)
            healthy.release(0)
            stalled.release(0)

            shed_before = _peek("corro.subs.shed.total")
            # enough event bytes to blow the 2 KiB lag bound
            for i in range(12):
                await insert(a, i, "x" * 400)

            assert await wait_until(
                lambda: stalled.done.done(), timeout=20.0
            ), "stalled sink was never shed"
            outcome = stalled.done.result()
            assert isinstance(outcome, SubLagging), outcome
            assert outcome.lag_bytes > 2048 or outcome.lag_batches > 4
            assert _peek("corro.subs.shed.total") > shed_before
            assert stalled.received == b"", (
                "a stalled transport must receive nothing"
            )

            # the DiffExecutor and the sibling keep delivering: rows
            # written AFTER the shed still arrive
            await insert(a, 100, "after-shed")
            assert await wait_until(
                lambda: b"after-shed" in bytes(healthy.received),
                timeout=20.0,
            ), "sibling stream stalled behind a shed laggard"
            assert not healthy.done.done(), "sibling must stay attached"
        finally:
            await _shutdown(a, api, client)

    asyncio.run(main())


def test_stalled_h2_client_is_shed_end_to_end():
    """The same shed through the REAL serving stack: a native-h2
    subscriber that stops reading its socket exhausts its flow-control
    windows; the fan-out writer clogs its sink, the lag bound trips,
    the server sheds — and a sibling subscriber on its own connection
    receives every event meanwhile."""

    async def main():
        net = MemNetwork(seed=65)
        a, api, client = await boot_with_api(net, "agent-shed-h2")
        a.config.subs.max_lag_bytes = 16 * 1024
        a.config.subs.max_lag_batches = 64
        from corrosion_tpu.client import CorrosionApiClient

        sib_client = CorrosionApiClient(api.addrs[0])
        lag_client = CorrosionApiClient(api.addrs[0])
        n_rows = 120
        got = []

        async def sibling():
            async for line in sib_client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True, raw=True
            ):
                if line.startswith('{"change":'):
                    got.append(line)
                    if len(got) >= n_rows:
                        return

        try:
            sib_task = asyncio.ensure_future(sibling())
            lag_it = lag_client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True
            ).__aiter__()
            await next_of(lag_it, "eoq")
            await asyncio.sleep(0.3)  # sibling subscribed too

            # stall the laggard: kill its frame pump so the socket is
            # never read again — windows stop being credited
            lag_client._session.h2._reader_task.cancel()

            shed_before = _peek("corro.subs.shed.total")
            for i in range(n_rows):
                await insert(a, i, "y" * 900)

            assert await wait_until(
                lambda: _peek("corro.subs.shed.total") > shed_before,
                timeout=30.0,
            ), "stalled h2 consumer was never shed"
            # sibling still drains the full event stream
            await asyncio.wait_for(sib_task, 60)
            assert len(got) >= n_rows
        finally:
            await _shutdown(a, api, client, sib_client, lag_client)

    asyncio.run(main())


def test_client_resumes_from_lagging_frame():
    """client.py handles the typed shed: on a `{"lagging": ...}`
    terminal the SubscriptionStream reconnects BY QUERY ID from its
    last change id — the matcher's changes log replays the gap and live
    events continue on the resumed stream."""

    async def main():
        net = MemNetwork(seed=66)
        a, api, client = await boot_with_api(net, "agent-resume")
        try:
            stream = client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True
            )
            it = stream.__aiter__()
            await next_of(it, "eoq")
            await insert(a, 1, "one")
            ev = await next_of(it, "change")
            assert ev["change"][2] == [1, "one"]

            # inject a shed exactly as the fan-out writer would issue it
            handle = api.subs.get(stream.query_id)
            assert handle is not None
            sink = handle._sinks[0]
            handle.loop.call_soon(
                sink._resolve, SubLagging(lag_bytes=9999, lag_batches=9)
            )

            # rows written around the shed must ALL arrive exactly once:
            # the log replay covers the reconnect gap
            await insert(a, 2, "two")
            await insert(a, 3, "three")
            seen = []
            while len(seen) < 2:
                ev = await next_of(it, "change", timeout=20.0)
                seen.append(ev["change"][2])
            assert seen == [[2, "two"], [3, "three"]]
        finally:
            await _shutdown(a, api, client)

    asyncio.run(main())

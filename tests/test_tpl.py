"""Template engine tests: compiler, rendering against a live API, watch
mode re-render on data change. Mirrors `klukai/src/tpl` coverage."""

from corrosion_tpu.runtime.tmpdb import fresh_db_path
import asyncio
import os

import pytest

from corrosion_tpu.admin import AdminServer
from corrosion_tpu.agent.run import make_broadcastable_changes, run, setup, shutdown
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.tpl import (
    QueryResponse,
    TemplateError,
    compile_template,
    parse_spec,
    render_once,
)

TEST_SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
)


def test_compile_literal_and_expr():
    t = compile_template("hello <%= 1 + 2 %> world")
    assert t({}) == "hello 3 world"


def test_compile_loop_and_if():
    t = compile_template(
        "<% for x in items %><% if x > 1 %><%= x %>,<% end %><% end %>"
    )
    assert t({"items": [1, 2, 3]}) == "2,3,"


def test_compile_else():
    t = compile_template(
        "<% for x in items %>"
        "<% if x % 2 == 0 %>e<% else %>o<% end %>"
        "<% end %>"
    )
    assert t({"items": [1, 2, 3, 4]}) == "oeoe"


def test_compile_unbalanced_raises():
    with pytest.raises(TemplateError):
        compile_template("<% for x in items %>never closed")
    with pytest.raises(TemplateError):
        compile_template("<% end %>")


def test_query_response_json_csv():
    qr = QueryResponse(["id", "name"], [[1, "ann"], [2, "bob"]])
    assert '"name": "ann"' in qr.to_json(pretty=True)
    assert qr.to_csv() == "id,name\r\n1,ann\r\n2,bob\r\n"
    rows = list(qr)
    assert rows[0]["name"] == "ann"
    assert rows[0].name == "ann"
    assert rows[1][0] == 2


def test_parse_spec():
    assert parse_spec("a.tpl:out.txt") == ("a.tpl", "out.txt", None)
    assert parse_spec("a.tpl:out.txt:echo hi") == ("a.tpl", "out.txt", "echo hi")
    with pytest.raises(TemplateError):
        parse_spec("just-a-src")


async def boot_api(tmp_path):
    cfg = Config()
    cfg.db.path = fresh_db_path()
    cfg.gossip.bind_addr = "a:1"
    cfg.api.bind_addr = ["127.0.0.1:0"]
    net = MemNetwork()
    agent = await setup(cfg, network=net)
    agent.store.apply_schema_sql(TEST_SCHEMA)
    await run(agent)
    api = ApiServer(agent)
    await api.start()
    return agent, api


async def insert(agent, rowid, text):
    await make_broadcastable_changes(
        agent,
        lambda tx: [
            tx.execute(
                "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                [rowid, text],
            )
        ],
    )


async def test_render_once_with_sql(tmp_path):
    agent, api = await boot_api(tmp_path)
    try:
        await insert(agent, 1, "alpha")
        await insert(agent, 2, "beta")
        src = tmp_path / "t.tpl"
        src.write_text(
            "entries:\n"
            "<% for row in sql('SELECT id, text FROM tests ORDER BY id') %>"
            "- <%= row.id %>: <%= row.text %>\n"
            "<% end %>"
            "host: <%= hostname() %>\n"
        )
        dst = tmp_path / "out.txt"
        await render_once(api.addrs[0], None, str(src), str(dst), None)
        out = dst.read_text()
        assert "- 1: alpha\n" in out
        assert "- 2: beta\n" in out
        assert "host: " in out
    finally:
        await api.stop()
        await shutdown(agent)


async def test_render_to_json_and_cmd(tmp_path):
    agent, api = await boot_api(tmp_path)
    try:
        await insert(agent, 1, "x")
        src = tmp_path / "t.tpl"
        src.write_text(
            "<%= sql('SELECT id, text FROM tests').to_json() %>"
        )
        dst = tmp_path / "out.json"
        marker = tmp_path / "ran.marker"
        await render_once(
            api.addrs[0], None, str(src), str(dst),
            f"touch {marker}",
        )
        assert dst.read_text() == '[{"id": 1, "text": "x"}]'
        assert marker.exists()
    finally:
        await api.stop()
        await shutdown(agent)


def test_row_cells_helpers():
    qr = QueryResponse(["id", "name"], [[1, None]])
    cells = list(qr)[0].cells()
    assert [(c.name, c.value) for c in cells] == [("id", 1), ("name", None)]
    assert not cells[0].is_null() and cells[1].is_null()
    assert cells[0].to_json() == "1"
    assert cells[1].to_string() == ""


async def test_exec_cmd_in_template(tmp_path, monkeypatch):
    """Templates can shell out via exec_cmd (argv, no shell) and inline
    the stdout — but only with the explicit CORRO_TPL_ALLOW_EXEC opt-in;
    failures and timeouts surface as TemplateError."""
    from corrosion_tpu.tpl import TemplateState

    agent, api = await boot_api(tmp_path)
    try:
        # default-off: without the opt-in a template cannot run commands
        loop0 = asyncio.get_running_loop()
        locked = TemplateState(api.addrs[0], None, loop0, False)
        with pytest.raises(TemplateError, match="disabled"):
            locked.exec_cmd("echo", "hi")
        monkeypatch.setenv("CORRO_TPL_ALLOW_EXEC", "1")
        src = tmp_path / "t.tpl"
        src.write_text("v=<%= exec_cmd('echo', 'hi').strip() %>")
        dst = tmp_path / "out.txt"
        await render_once(api.addrs[0], None, str(src), str(dst), None)
        assert dst.read_text() == "v=hi"

        loop = asyncio.get_running_loop()
        state = TemplateState(api.addrs[0], None, loop, False)
        with pytest.raises(TemplateError, match="exited 3"):
            state.exec_cmd("sh", "-c", "exit 3")
        with pytest.raises(TemplateError, match="timed out"):
            state.exec_cmd("sleep", "5", timeout=0.2)
        with pytest.raises(TemplateError, match="failed"):
            state.exec_cmd("definitely-not-a-binary")
    finally:
        await api.stop()
        await shutdown(agent)


async def test_watch_rerenders_on_data_change(tmp_path):
    from corrosion_tpu.tpl import _watch_one

    agent, api = await boot_api(tmp_path)
    try:
        await insert(agent, 1, "first")
        src = tmp_path / "t.tpl"
        src.write_text(
            "<% for r in sql('SELECT text FROM tests ORDER BY id') %>"
            "<%= r.text %>;<% end %>"
        )
        dst = tmp_path / "out.txt"
        task = asyncio.ensure_future(
            _watch_one(api.addrs[0], None, f"{src}:{dst}", None)
        )
        # initial render
        for _ in range(100):
            if dst.exists() and dst.read_text() == "first;":
                break
            await asyncio.sleep(0.05)
        assert dst.read_text() == "first;"

        # data change → re-render
        await insert(agent, 2, "second")
        for _ in range(100):
            if dst.exists() and dst.read_text() == "first;second;":
                break
            await asyncio.sleep(0.05)
        assert dst.read_text() == "first;second;"
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
    finally:
        await api.stop()
        await shutdown(agent)

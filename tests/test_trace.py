"""Tracing: W3C traceparent round-trip, context propagation across the
sync protocol wire, slow-query accounting. Mirrors SURVEY §5 tracing
(sync.rs:33-67 SyncTraceContextV1 propagation)."""

import asyncio

from corrosion_tpu.runtime import trace as tr
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.types.codec import (
    SyncTraceContext,
    decode_bi_payload,
    encode_bi_payload_sync_start,
)
from corrosion_tpu.types.actor import ActorId, ClusterId


def test_traceparent_roundtrip():
    with tr.span("outer") as sp:
        tp = sp.ctx.traceparent()
        assert tp.startswith("00-")
        parsed = tr.parse_traceparent(tp)
        assert parsed.trace_id == sp.ctx.trace_id
        assert parsed.span_id == sp.ctx.span_id
        assert parsed.sampled


def test_parse_rejects_garbage():
    assert tr.parse_traceparent(None) is None
    assert tr.parse_traceparent("") is None
    assert tr.parse_traceparent("junk") is None
    assert tr.parse_traceparent("00-" + "0" * 32 + "-" + "1" * 16 + "-01") is None


def test_child_span_shares_trace_id():
    with tr.span("parent") as p:
        with tr.span("child") as c:
            assert c.ctx.trace_id == p.ctx.trace_id
            assert c.ctx.span_id != p.ctx.span_id
            assert tr.current_traceparent() == c.ctx.traceparent()
        assert tr.current_traceparent() == p.ctx.traceparent()
    assert tr.current_traceparent() is None


def test_continue_from_adopts_remote_trace():
    remote = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with tr.continue_from(remote, "sync.server") as sp:
        assert sp.ctx.trace_id == "ab" * 16
        assert sp.ctx.span_id != "cd" * 8  # new span, same trace
    # bad incoming context → fresh trace, never an error
    with tr.continue_from("garbage", "sync.server") as sp:
        assert len(sp.ctx.trace_id) == 32


def test_trace_context_rides_sync_start_wire():
    aid = ActorId.new_random()
    with tr.span("sync.client") as sp:
        frame = encode_bi_payload_sync_start(
            aid,
            trace=SyncTraceContext(traceparent=sp.ctx.traceparent()),
            cluster_id=ClusterId(3),
        )
    got_aid, got_trace, got_cid = decode_bi_payload(frame)
    assert got_aid == aid
    assert got_cid == ClusterId(3)
    assert tr.parse_traceparent(got_trace.traceparent).trace_id == sp.ctx.trace_id


def test_trace_context_rides_eager_broadcast_wire():
    """r11: the eager dissemination path carries a traceparent too (sync
    already does via SyncStart), so cross-node spans stitch on BOTH
    paths.  The stamp rides the version-gated envelope ext of the uni
    payload."""
    from corrosion_tpu.types.base import Timestamp
    from corrosion_tpu.types.change import ChangeV1, ChangesetEmpty
    from corrosion_tpu.types.codec import (
        decode_uni_payload,
        encode_uni_payload,
    )

    aid = ActorId.new_random()
    with tr.span("write.local") as sp:
        cv = ChangeV1(
            actor_id=aid,
            changeset=ChangesetEmpty(versions=(3, 3), ts=Timestamp(9)),
            traceparent=sp.ctx.traceparent(),
        )
        frame = encode_uni_payload(cv, ClusterId(2))
    got, got_cid = decode_uni_payload(frame)
    assert got_cid == ClusterId(2)
    assert tr.parse_traceparent(got.traceparent).trace_id == sp.ctx.trace_id
    # the receiver adopts it exactly like the sync server does
    with tr.continue_from(got.traceparent, "broadcast.recv") as child:
        assert child.ctx.trace_id == sp.ctx.trace_id
        assert child.ctx.span_id != sp.ctx.span_id


def test_timed_query_counts_slow():
    import time as _time

    before = METRICS.counter("corro_slow_queries_total").value
    old = tr.SLOW_QUERY_S
    tr.SLOW_QUERY_S = 0.01
    try:
        with tr.timed_query("SELECT slow"):
            _time.sleep(0.02)
    finally:
        tr.SLOW_QUERY_S = old
    assert METRICS.counter("corro_slow_queries_total").value == before + 1


def test_span_context_isolated_per_task():
    async def main():
        seen = {}

        async def worker(name):
            with tr.span(name) as sp:
                await asyncio.sleep(0.01)
                seen[name] = tr.current_context().trace_id
                assert tr.current_context().span_id == sp.ctx.span_id

        await asyncio.gather(worker("a"), worker("b"))
        assert seen["a"] != seen["b"]

    asyncio.run(main())

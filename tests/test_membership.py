"""SWIM membership over the in-memory network: join, converge, fail, refute.

Mirrors the reference's in-process multi-agent test pattern
(`klukai-agent/src/agent/tests.rs`) at the membership layer.
"""

import asyncio
import random

from corrosion_tpu.agent.members import Members, ring_for_rtt
from corrosion_tpu.agent.membership import (
    Membership,
    MemberState,
    MemberUpdate,
    Notification,
    SwimConfig,
)
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime.tripwire import Tripwire
from corrosion_tpu.types.actor import Actor, ActorId
from corrosion_tpu.types.base import Timestamp

FAST = SwimConfig(
    probe_period=0.05,
    probe_rtt=0.02,
    suspicion_mult=1.0,
)


def mk_node(net: MemNetwork, n: int, cfg=FAST):
    addr = f"node{n}"
    actor = Actor(
        id=ActorId(bytes([n]) * 16), addr=addr, ts=Timestamp.from_unix(n)
    )
    transport = net.transport(addr)
    ms = Membership(actor, transport, cfg, rng=random.Random(n))

    async def on_uni(src, data):
        pass

    async def on_bi(stream):
        stream.close()

    net.listener(addr).serve(ms.handle_datagram, on_uni, on_bi)
    return ms


async def wait_until(pred, timeout=10.0, step=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return pred()


def test_down_updates_get_deeper_carrier_budget():
    """A DOWN entering the dissemination queue carries
    down_transmissions_mult x the infection budget of ALIVE/SUSPECT
    chatter (extinction of a DOWN costs a straggler a full
    self-discovery round; see SwimConfig.down_transmissions_mult)."""
    net = MemNetwork(seed=3)
    ms = mk_node(net, 1)
    peer = Actor(
        id=ActorId(bytes([9]) * 16), addr="node9", ts=Timestamp.from_unix(9)
    )
    base = ms.config.max_transmissions(ms.cluster_size)
    ms._disseminate(MemberUpdate(peer, 0, MemberState.ALIVE))
    assert ms._queue[peer.id].sends_left == base
    ms._disseminate(MemberUpdate(peer, 0, MemberState.DOWN))
    assert ms._queue[peer.id].sends_left == (
        base * ms.config.down_transmissions_mult
    )


def test_three_nodes_converge_and_detect_failure():
    async def main():
        net = MemNetwork(seed=7)
        tw = Tripwire()
        nodes = [mk_node(net, i + 1) for i in range(3)]
        for ms in nodes:
            ms.start(tw)
        # join: 2 and 3 announce to 1
        await nodes[1].announce("node1")
        await nodes[2].announce("node1")

        assert await wait_until(
            lambda: all(ms.cluster_size == 3 for ms in nodes)
        ), [ms.cluster_size for ms in nodes]

        # no false positives while healthy
        await asyncio.sleep(0.3)
        assert all(ms.cluster_size == 3 for ms in nodes)

        # kill node3; 1 and 2 must converge on cluster_size == 2
        await nodes[2].stop()
        net.take_down("node3")
        assert await wait_until(
            lambda: nodes[0].cluster_size == 2 and nodes[1].cluster_size == 2
        ), [ms.cluster_size for ms in nodes[:2]]

        tw.trip()
        for ms in nodes[:2]:
            await ms.stop()

    asyncio.run(main())


def test_suspected_node_refutes_and_survives():
    async def main():
        net = MemNetwork(seed=3)
        tw = Tripwire()
        notes = []
        nodes = [mk_node(net, i + 1) for i in range(3)]
        nodes[2].on_notification = lambda n, a: notes.append(n)
        for ms in nodes:
            ms.start(tw)
        await nodes[1].announce("node1")
        await nodes[2].announce("node1")
        assert await wait_until(
            lambda: all(ms.cluster_size == 3 for ms in nodes)
        )

        # brief partition: node3 unreachable from 1 and 2, but still alive
        net.partition("node1", "node3")
        net.partition("node2", "node3")
        assert await wait_until(
            lambda: any(
                m.state.name == "SUSPECT"
                for ms in nodes[:2]
                for m in ms.members.values()
            ),
            timeout=5.0,
        )
        # heal before the suspicion window expires at 1s (mult=1 ⇒ ~0.1s
        # base window but state_since resets on re-suspicion) — the
        # suspect must refute with a higher incarnation and stay a member
        net.heal("node1", "node3")
        net.heal("node2", "node3")
        ok = await wait_until(
            lambda: all(ms.cluster_size == 3 for ms in nodes), timeout=5.0
        )
        if not ok:
            # a suspect that expired to DOWN must renew and rejoin
            await nodes[2].announce("node1")
            assert await wait_until(
                lambda: all(ms.cluster_size == 3 for ms in nodes),
                timeout=5.0,
            )
        tw.trip()
        for ms in nodes:
            await ms.stop()

    asyncio.run(main())


def test_graceful_leave():
    async def main():
        net = MemNetwork(seed=5)
        tw = Tripwire()
        nodes = [mk_node(net, i + 1) for i in range(3)]
        for ms in nodes:
            ms.start(tw)
        await nodes[1].announce("node1")
        await nodes[2].announce("node1")
        assert await wait_until(
            lambda: all(ms.cluster_size == 3 for ms in nodes)
        )
        await nodes[2].leave()
        await nodes[2].stop()
        assert await wait_until(
            lambda: nodes[0].cluster_size == 2 and nodes[1].cluster_size == 2,
            timeout=5.0,
        )
        tw.trip()
        for ms in nodes[:2]:
            await ms.stop()

    asyncio.run(main())


def test_members_rtt_rings():
    m = Members()
    a = Actor(id=ActorId(b"\x01" * 16), addr="a:1", ts=Timestamp.from_unix(1))
    assert m.add_member(a) is True
    assert m.add_member(a) is False  # refresh, not new
    m.observe_rtt("a:1", 0.002)  # 2ms -> ring 0
    assert m.get(a.id).ring == 0
    for _ in range(20):
        m.observe_rtt("a:1", 0.120)  # 120ms -> ring 4
    assert m.get(a.id).ring == 4
    assert ring_for_rtt(5.9) == 0
    assert ring_for_rtt(250.0) == 5

    # stale down about an old identity must not remove the renewed one
    renewed = a.renew()
    m.add_member(renewed)
    assert m.remove_member(a) is False
    assert m.remove_member(renewed) is True
    assert len(m) == 0

"""The per-PR bench smoke entry stays runnable and honest.

`scripts/bench_smoke.py` is the tier-1-safe bench point each PR banks
(BENCH_PR*.json): CPU-forced, miniature pview convergence, sha-stamped.
This drives it end-to-end at a sub-second shape and checks the contract
the trajectory depends on: exit 0 only with a converged record, the
artifact carries a code fingerprint matching the tree NOW, the platform
is the forced CPU, and the convergence stats clear the four-term bar.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_writes_converged_sha_stamped_record(tmp_path):
    out = tmp_path / "BENCH_PRtest.json"
    env = dict(
        os.environ,
        BENCH_SMOKE_N="512",
        BENCH_SMOKE_SLOTS="64",
        BENCH_SMOKE_MAX_TICKS="400",
        BENCH_SMOKE_SKIP_CHURN="1",
        BENCH_SMOKE_OUT=str(out),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_smoke.py"),
         "test"],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    det = rec["detail"]
    assert det["platform"] == "cpu"  # forced: points must be comparable
    assert det["stable_tick"] is not None
    assert det["stats"]["false_positive"] == 0.0
    assert det["stats"]["pv_coverage"] >= 0.99

    # fingerprint discipline: stamped over the measured files, matching
    # the tree at test time (same check bench.py's replay gate applies)
    import hashlib

    for rel, short in det["code_sha"].items():
        with open(os.path.join(REPO, rel), "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest()[:12] == short, rel

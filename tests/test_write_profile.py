"""WRITE_PROFILE.json guards (r23): the banked write-path attribution
must stay coherent and the always-on sampler affordable.

Same discipline as test_ingest_bench.py: assert on the BANKED document
(structure + invariants), don't re-run the bench in tier-1.  The bank
is re-cut by `python scripts/bench_ingest.py --profile`.
"""

import json
import os

import pytest

from corrosion_tpu.runtime.profiler import WRITE_BUCKETS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BANK = os.path.join(REPO, "WRITE_PROFILE.json")

# the acceptance bar: always-on sampling may cost the w16 write plane
# at most this fraction of its wall
MAX_OVERHEAD_PCT = 2.0


@pytest.fixture(scope="module")
def doc():
    assert os.path.exists(BANK), (
        "WRITE_PROFILE.json missing — run "
        "`python scripts/bench_ingest.py --profile`"
    )
    with open(BANK) as f:
        return json.load(f)


def test_five_buckets_partition_the_commit_wall(doc):
    buckets = doc["buckets_secs"]
    assert set(buckets) == set(WRITE_BUCKETS)
    assert all(v >= 0.0 for v in buckets.values()), buckets
    wall = doc["wall_secs"]
    assert wall > 0.0
    # the buckets are constructed to PARTITION submit→resolve; banked
    # coverage under 90% means a stamp went missing
    assert sum(buckets.values()) >= 0.9 * wall
    assert doc["coverage_pct"] >= 90.0
    assert doc["bucket_commits"] > 0


def test_sampler_overhead_within_budget(doc):
    ov = doc["overhead"]
    # duty accounting — exact busy/wall under the live w16 load
    assert 0.0 <= ov["overhead_pct"] <= MAX_OVERHEAD_PCT, ov
    assert ov["duty_phase_max_pct"] >= ov["overhead_pct"] - 1e-9
    # the corroborating throughput A/B is banked with its noise floor,
    # not trusted as a point estimate: it must exist and be well-formed
    ab = ov["ab"]
    assert ab["reps"] >= 4
    assert ab["rows_per_s_off"] > 0 and ab["rows_per_s_on"] > 0
    lo, hi = ab["pair_delta_spread_pct"]
    assert lo <= ab["median_paired_delta_pct"] <= hi


def test_adaptive_shed_was_live(doc):
    # the governor must have been exercised during the banked run —
    # an overhead number measured with the shed ladder inert says
    # nothing about production behavior.  r24: the bench now PROVES
    # the ladder with a deterministic forced-budget probe during
    # warmup (the r23 bank only shed by luck on a warmup spike; the
    # faster write path holds steady duty well under budget, so a
    # run that hopes for an organic shed would bank sheds_total=0)
    ov = doc["overhead"]
    probe = ov["governor_probe"]
    assert probe["shed_fired"] is True, probe
    assert probe["forced_budget_pct"] < MAX_OVERHEAD_PCT
    assert ov["sheds_total"] >= 1 or (
        doc["detail"]["sampler"]["sheds_total"] >= 1
    )
    assert ov["hz_effective"] > 0


def test_detail_attribution_is_coherent(doc):
    det = doc["detail"]
    # sqlite COMMIT flush wall rides inside the commit pipeline
    assert det["commit_fsync_count"] > 0
    assert 0.0 < det["commit_fsync_secs"] < doc["wall_secs"]
    # the w1 rung's statement shapes were profiled
    assert any(k.startswith("insert:") for k in det["stmt_secs"])
    assert det["stmt_rows"] and det["stmt_rows"][0]["count"] > 0
    census = det["sampler"]
    assert census["enabled"] is True
    assert census["busy_secs_total"] > 0.0
    assert det["w1_rows_per_s"] > 0


def test_code_sha_stamps_the_profiled_files(doc):
    shas = doc["code_sha"]
    for path in (
        "corrosion_tpu/runtime/profiler.py",
        "corrosion_tpu/agent/run.py",
        "corrosion_tpu/store/crdt.py",
        "scripts/bench_ingest.py",
    ):
        assert shas.get(path) and shas[path] != "missing", path

"""Sync-plane fault tests (r17 catch-up round): mid-stream peer death
resuming on a sibling inside one sync call, the wire-level schema gate
on snapshot bootstrap, and stale-snapshot + delta top-up pinned
byte-identical against a pure-delta replica.

Shapes are deliberately tiny (tier-1 runs near the 870 s kill); the
100k/1M rungs live in scripts/bench_sync.py → SYNC_SCALE.json."""

import asyncio

from corrosion_tpu.agent.ingest import (
    apply_fully_buffered_loop,
    handle_changes,
)
from corrosion_tpu.agent.run import (
    make_broadcastable_changes,
    setup,
    shutdown,
)
from corrosion_tpu.agent.syncer import parallel_sync
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.net.transport import TransportError
from corrosion_tpu.runtime.metrics import METRICS

from tests.test_agent import TEST_SCHEMA, boot, fast_config, wait_until

# CRDT merge state; ts excluded — it is origin-local bookkeeping and a
# replica applying remote changes stores 0 there on the standing delta
# path (route-dependent, not convergence-relevant)
CLOCK_SQL = (
    "SELECT pk, cid, col_version, db_version, seq, site_id"
    " FROM tests__crdt_clock ORDER BY pk, cid, db_version, seq"
)


def count_rows(agent) -> int:
    conn = agent.store.read_conn()
    try:
        return conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
    finally:
        conn.close()


def clock_rows(agent):
    conn = agent.store.read_conn()
    try:
        return [tuple(r) for r in conn.execute(CLOCK_SQL)]
    finally:
        conn.close()


def peek(name: str, **labels) -> float:
    for _kind, sname, slabels, value in METRICS.snapshot():
        if sname == name and slabels == labels:
            return value
    return 0.0


async def load_versions(agent, n, rows_per=2, base=0):
    for v in range(n):
        await make_broadcastable_changes(
            agent,
            lambda tx, v=v: [
                tx.execute(
                    "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                    ((base + v) * rows_per + k, f"r{base + v}-{k}"),
                )
                for k in range(rows_per)
            ],
        )


class _DyingStream:
    """Proxy that kills the session after `frames` received frames —
    the deterministic mid-stream peer death."""

    def __init__(self, inner, frames):
        self.inner = inner
        self.left = frames

    async def send(self, payload):
        await self.inner.send(payload)

    async def recv(self):
        if self.left <= 0:
            raise TransportError("injected mid-stream death")
        self.left -= 1
        return await self.inner.recv()

    async def finish(self):
        await self.inner.finish()

    def close(self):
        self.inner.close()

    @property
    def peer(self):
        return self.inner.peer


def test_mid_stream_peer_death_resumes_on_sibling():
    """A dies 4 frames into serving C; the SAME parallel_sync call
    releases A's unserved ranges and re-claims them from B — full
    convergence with nothing lost and nothing double-applied."""

    async def main():
        net = MemNetwork(seed=3)
        a = await boot(net, "agent-a")
        b = await boot(net, "agent-b", bootstrap=("agent-a",))
        await load_versions(a, 40)
        assert await wait_until(lambda: count_rows(b) == 80, timeout=60)

        cfg = fast_config("agent-c")
        cfg.sync.snapshot = False
        c = await setup(cfg, network=net)
        c.store.apply_schema_sql(TEST_SCHEMA)
        c.tracker.spawn(handle_changes(c))
        c.tracker.spawn(apply_fully_buffered_loop(c))
        try:
            real_open = c.transport.open_bi
            died = {"n": 0}

            async def open_bi(addr):
                stream = await real_open(addr)
                if addr == "agent-a" and died["n"] == 0:
                    died["n"] += 1
                    return _DyingStream(stream, frames=4)
                return stream

            c.transport.open_bi = open_bi
            waves0 = peek("corro.sync.resume.waves.total")
            freed0 = peek("corro.sync.resume.versions.total")
            await parallel_sync(c, [a.actor, b.actor])
            assert died["n"] == 1, "fault was never injected"
            assert peek("corro.sync.resume.waves.total") > waves0
            assert peek("corro.sync.resume.versions.total") > freed0
            assert await wait_until(lambda: count_rows(c) == 80, timeout=30)
            # nothing lost, nothing double-applied: the CRDT merge
            # state is exactly the origin's (row count pins duplicates —
            # a double apply is idempotent but a clock-row mismatch or
            # missing version is not)
            assert await wait_until(
                lambda: clock_rows(c) == clock_rows(a), timeout=10
            )
        finally:
            await shutdown(c)
            await shutdown(b)
            await shutdown(a)

    asyncio.run(main())


def test_cold_node_snapshot_bootstrap_converges():
    """A cold node whose gap exceeds the heuristic installs the peer
    snapshot through the locked swap and tops up by delta — one e2e
    pass over the whole plane (probe → fetch → install → top-up)."""

    async def main():
        net = MemNetwork(seed=7)
        a = await boot(net, "agent-a")
        await load_versions(a, 30, rows_per=3)
        # wait for the broadcast backlog to DRAIN, not a fixed sleep:
        # the pending heap's decaying resend schedule (~1.4 s at the
        # n=1 transmission budget) outlives a 0.7 s nap, and a
        # surviving backlog floods the cold joiner with every version —
        # rows converge by broadcast and the snapshot path never runs.
        # The settle nap first: freshly-queued changes take one loop
        # interval to even REACH the pending heap's gauge
        await asyncio.sleep(0.3)
        assert await wait_until(
            lambda: peek("corro.broadcast.pending.count") == 0,
            timeout=10,
        )
        installs0 = peek("corro.snapshot.install.total")
        serves0 = peek("corro.snapshot.serve.total")
        cfg = fast_config("agent-c", bootstrap=("agent-a",))
        cfg.sync.snapshot_min_gap_versions = 10
        c = await boot(net, "agent-c", bootstrap=("agent-a",), cfg=cfg)
        try:
            # the install is the thing under test — wait for IT, not
            # for row convergence (the delta top-up can land the last
            # rows while the swap is still mid-flight)
            assert await wait_until(
                lambda: peek("corro.snapshot.install.total")
                == installs0 + 1,
                timeout=60,
            )
            assert await wait_until(lambda: count_rows(c) == 90, timeout=60)
            assert peek("corro.snapshot.serve.total") == serves0 + 1
            assert c.catchup_census.get("state") == "installed"
            assert c.catchup_census.get("watermark_versions", 0) >= 30
            assert await wait_until(
                lambda: clock_rows(c) == clock_rows(a), timeout=10
            )
            # identity preserved: the installed db answers with C's id
            assert c.store.site_id == c.actor_id
        finally:
            await shutdown(c)
            await shutdown(a)

    asyncio.run(main())


def test_snapshot_schema_mismatch_refused_over_wire():
    """A cold node running a different schema generation is refused by
    the serving side (typed rejection) and falls back cleanly — no
    swap, no wedge."""

    async def main():
        from corrosion_tpu.agent.catchup import maybe_snapshot_bootstrap

        net = MemNetwork(seed=11)
        a = await boot(net, "agent-a")
        await load_versions(a, 20)
        cfg = fast_config("agent-x")
        cfg.sync.snapshot_min_gap_versions = 5
        x = await setup(cfg, network=net)
        x.store.apply_schema_sql(
            "CREATE TABLE other (id INTEGER NOT NULL PRIMARY KEY, v TEXT);"
        )
        try:
            rejected0 = peek(
                "corro.snapshot.serve.rejected.total", reason="schema"
            )
            installs0 = peek("corro.snapshot.install.total")
            ok = await maybe_snapshot_bootstrap(x, [a.actor])
            assert ok is False
            assert (
                peek("corro.snapshot.serve.rejected.total", reason="schema")
                == rejected0 + 1
            )
            assert peek("corro.snapshot.install.total") == installs0
            # the refused node's database is untouched and writable
            with x.store.write_tx(x.clock.new_timestamp()) as tx:
                tx.execute(
                    "INSERT INTO other (id, v) VALUES (1, 'still-alive')"
                )
        finally:
            await shutdown(x)
            await shutdown(a)

    asyncio.run(main())


def test_install_invalidates_ingest_seen_cache():
    """The r17 fire-grind bug, pinned: a change applied BEFORE a
    database swap leaves its key in handle_changes' seen-cache while
    the swap drops its data — without the epoch bump, the re-served
    change is skipped as 'seen' forever and the version can only limp
    back in via cache eviction."""

    async def main():
        from corrosion_tpu.agent.handle import ChangeSource
        from corrosion_tpu.types.actor import ActorId
        from corrosion_tpu.types.base import Timestamp
        from corrosion_tpu.types.codec import chunked_change_v1
        from corrosion_tpu.types.change import Change

        net = MemNetwork(seed=17)
        cfg = fast_config("agent-e")
        e = await setup(cfg, network=net)
        e.store.apply_schema_sql(TEST_SCHEMA)
        e.tracker.spawn(handle_changes(e))
        try:
            origin = ActorId(b"\x42" * 16)
            ts = Timestamp.now()
            changes = [
                Change(
                    table="tests", pk=b"\x01\x09\x07", cid="text",
                    val="hello", col_version=1, db_version=1, seq=0,
                    site_id=origin.bytes16, cl=1, ts=ts,
                )
            ]
            [cv] = chunked_change_v1(origin, 1, changes, 0, ts)
            await e.tx_changes.send((cv, ChangeSource.SYNC))
            assert await wait_until(lambda: count_rows(e) == 1, timeout=15)

            # simulate the swap: the data vanishes, the bookie forgets,
            # but the seen-cache still remembers the change
            with e.store._lock:
                e.store._conn.execute("DELETE FROM tests")
                e.store._conn.execute("DELETE FROM tests__crdt_clock")
                e.store._conn.commit()
            e.store._dv_cache.clear()
            from corrosion_tpu.store.bookkeeping import BookedVersions

            e.bookie.insert(origin, BookedVersions(origin))
            e.ingest_epoch += 1  # what snapshot_bootstrap does

            await e.tx_changes.send((cv, ChangeSource.SYNC))
            assert await wait_until(lambda: count_rows(e) == 1, timeout=15), (
                "re-served change was shadowed by the stale seen-cache"
            )
        finally:
            await shutdown(e)

    asyncio.run(main())


def test_own_write_during_transfer_refuses_install():
    """The local-ahead guard's TOCTOU window, pinned: an own-origin
    write that commits AFTER the header-time coverage check but BEFORE
    the write-gate permit must still refuse the install — the swap
    would silently drop an acked local write and regress the node's
    own version head (re-issuing broadcast version numbers with
    different contents)."""

    async def main():
        import corrosion_tpu.agent.catchup as catchup_mod
        from corrosion_tpu.agent.catchup import maybe_snapshot_bootstrap

        net = MemNetwork(seed=19)
        a = await boot(net, "agent-a")
        await load_versions(a, 30)
        cfg = fast_config("agent-w")
        cfg.sync.snapshot_min_gap_versions = 10
        w = await setup(cfg, network=net)
        w.store.apply_schema_sql(TEST_SCHEMA)
        try:
            real_fetch = catchup_mod._fetch_snapshot

            async def fetch_then_write(agent, peer, tmp_db):
                header = await real_fetch(agent, peer, tmp_db)
                # lands in the TOCTOU window: past the header-time
                # check, before snapshot_bootstrap takes the write gate
                await make_broadcastable_changes(
                    agent,
                    lambda tx: [
                        tx.execute(
                            "INSERT INTO tests (id, text)"
                            " VALUES (9999, 'mine')"
                        )
                    ],
                )
                return header

            catchup_mod._fetch_snapshot = fetch_then_write
            refused0 = peek(
                "corro.snapshot.install.refused.total", reason="local_ahead"
            )
            installs0 = peek("corro.snapshot.install.total")
            try:
                ok = await maybe_snapshot_bootstrap(w, [a.actor])
            finally:
                catchup_mod._fetch_snapshot = real_fetch
            assert ok is False
            assert (
                peek(
                    "corro.snapshot.install.refused.total",
                    reason="local_ahead",
                )
                == refused0 + 1
            )
            assert peek("corro.snapshot.install.total") == installs0
            # the acked write survived, in the db and in the bookie
            conn = w.store.read_conn()
            try:
                row = conn.execute(
                    "SELECT text FROM tests WHERE id = 9999"
                ).fetchone()
            finally:
                conn.close()
            assert row is not None and row[0] == "mine"
            booked = w.bookie.get(w.actor_id)
            assert booked is not None
            with booked.read() as bv:
                assert bv.last() == 1
        finally:
            await shutdown(w)
            await shutdown(a)

    asyncio.run(main())


def test_install_replaces_stale_bookie_entries():
    """The post-swap bookie rebuild must be an exact replacement: a
    pre-install entry for an actor ABSENT from the snapshot claims
    versions the swap dropped, and an insert-merge would let it
    survive — delta top-up then never re-fetches them."""

    async def main():
        from corrosion_tpu.agent.catchup import maybe_snapshot_bootstrap
        from corrosion_tpu.store.bookkeeping import BookedVersions
        from corrosion_tpu.types.actor import ActorId

        net = MemNetwork(seed=23)
        a = await boot(net, "agent-a")
        await load_versions(a, 30)
        cfg = fast_config("agent-y")
        cfg.sync.snapshot_min_gap_versions = 10
        cfg.sync.max_concurrent_snapshot_serves = 5
        y = await setup(cfg, network=net)
        y.store.apply_schema_sql(TEST_SCHEMA)
        try:
            # the [sync] serve-permit knob is wired through agent build
            assert y.snapshot_serve_sem._value == 5
            ghost = ActorId(b"\x99" * 16)
            bv = BookedVersions(ghost)
            bv.max = 5  # claims versions that exist in no database
            y.bookie.insert(ghost, bv)
            ok = await maybe_snapshot_bootstrap(y, [a.actor])
            assert ok is True
            assert y.bookie.get(ghost) is None, (
                "stale bookie entry survived the snapshot install"
            )
            # origin and self are exactly what the installed db knows
            assert y.bookie.get(a.actor_id) is not None
            assert y.bookie.get(y.actor_id) is not None
        finally:
            await shutdown(y)
            await shutdown(a)

    asyncio.run(main())


def test_failed_bootstrap_keeps_probe_rate_limit_stamp():
    """A failed bootstrap's census record must not erase
    last_probe_mono — wholesale replacement reset the 15 s state-probe
    rate limit on every failure, so a digestless cold node paid a
    probe dial every sync round."""

    async def main():
        from corrosion_tpu.agent.catchup import snapshot_bootstrap
        from corrosion_tpu.types.actor import Actor, ActorId

        net = MemNetwork(seed=29)
        cfg = fast_config("agent-z")
        z = await setup(cfg, network=net)
        z.store.apply_schema_sql(TEST_SCHEMA)
        try:
            z.catchup_census["last_probe_mono"] = 123.0
            ghost = Actor(
                id=ActorId(b"\x31" * 16),
                addr="nowhere",  # dial fails: counted bootstrap failure
                ts=z.clock.new_timestamp(),
                cluster_id=z.cluster_id,
            )
            ok = await snapshot_bootstrap(z, ghost)
            assert ok is False
            assert z.catchup_census.get("state") == "failed"
            assert z.catchup_census.get("last_probe_mono") == 123.0
        finally:
            await shutdown(z)

    asyncio.run(main())


def test_stale_snapshot_topup_matches_pure_delta():
    """Bootstrap from a STALE snapshot (built at version 10 of 20) plus
    delta top-up must land on the same tables — user rows and CRDT
    clock state — as a pure-delta replica and as the origin."""

    async def main():
        from corrosion_tpu.agent.catchup import ensure_snapshot_cache

        net = MemNetwork(seed=13)
        a = await boot(net, "agent-a")
        await load_versions(a, 10)
        # freeze the serve-side cache at version 10...
        cache = ensure_snapshot_cache(a)
        cache.ensure_fresh(
            a.store.schema, a.store.site_id.bytes16, a.bookie, 3600.0
        )
        assert cache.header.watermark_total() == 10
        a.config.sync.snapshot_max_age_secs = 3600.0  # keep it stale
        # ...then move the origin 10 versions past it
        await load_versions(a, 10, base=10)
        await asyncio.sleep(0.7)  # let the broadcast backlog expire

        cfg_c = fast_config("agent-c", bootstrap=("agent-a",))
        cfg_c.sync.snapshot_min_gap_versions = 5
        c = await boot(net, "agent-c", bootstrap=("agent-a",), cfg=cfg_c)
        cfg_d = fast_config("agent-d", bootstrap=("agent-a",))
        cfg_d.sync.snapshot = False
        d = await boot(net, "agent-d", bootstrap=("agent-a",), cfg=cfg_d)
        try:
            assert await wait_until(
                lambda: count_rows(c) == 40 and count_rows(d) == 40,
                timeout=90,
            )
            # C really took the stale-snapshot path (watermark 10 < 20)
            assert c.catchup_census.get("state") == "installed"
            assert c.catchup_census.get("watermark_versions") == 10
            # the pin: stale snapshot + top-up ≡ pure delta ≡ origin
            assert await wait_until(
                lambda: clock_rows(c) == clock_rows(a), timeout=10
            )
            assert clock_rows(d) == clock_rows(a)
            conn_c, conn_d = c.store.read_conn(), d.store.read_conn()
            try:
                tc = conn_c.execute(
                    "SELECT * FROM tests ORDER BY id"
                ).fetchall()
                td = conn_d.execute(
                    "SELECT * FROM tests ORDER BY id"
                ).fetchall()
            finally:
                conn_c.close()
                conn_d.close()
            assert [tuple(r) for r in tc] == [tuple(r) for r in td]
        finally:
            await shutdown(d)
            await shutdown(c)
            await shutdown(a)

    asyncio.run(main())

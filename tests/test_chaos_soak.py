"""Deterministic chaos soak under strict invariants (VERDICT r4 #8).

The reference delegates chaos to the Antithesis hypervisor (SURVEY §4):
production code carries always/sometimes/unreachable assertions and the
deterministic environment drives faults until the "sometimes" coverage
contract is met.  This soak is the in-repo equivalent: a seeded fault
schedule (datagram loss, partition + divergent writes, agent restart
with on-disk resume, permanent crash) over real in-process agents with
`CORRO_INVARIANTS=strict` — any always-invariant violation raises — and
an exit assertion that every registered "sometimes" coverage marker
actually fired.  Progress-based bounds throughout (r4 weak #6).

`scripts/chaos_soak.py` runs this same soak standalone (twice, for the
flake-free-repeat requirement) and banks CHAOS_SOAK.json.
"""

from __future__ import annotations

import asyncio
import random

from corrosion_tpu.agent.membership import SwimConfig
from corrosion_tpu.net.mem import LinkFaults, MemNetwork
from corrosion_tpu.runtime import invariants

from tests.test_agent import (
    TEST_SCHEMA,
    count_rows,
    fast_config,
    insert,
    wait_progress,
)

# the coverage contract: every marker the production code registers
# must fire under this soak (syncer/broadcast/ingest)
EXPECTED_SOMETIMES = {
    "changes broadcast",
    "syncs with other nodes",
    "buffered version drained",
}


async def run_soak(seed: int) -> dict:
    """One full soak; returns the summary dict (asserts internally)."""
    from corrosion_tpu.agent.run import run, setup, shutdown

    rng = random.Random(seed)
    invariants.reset_sometimes()
    net = MemNetwork(seed=seed, faults=LinkFaults(datagram_loss=0.10))
    summary: dict = {"seed": seed, "phases": []}

    # FAST_SWIM timings with Lifeguard ON (r9): under full-suite load on
    # a 1-core host the soak process itself gets descheduled for longer
    # than the ~0.13 s suspicion window, and a vanilla detector turns
    # that self-lag into false suspicions of healthy peers (the r11
    # flake).  LHM-scaled timeouts are the designed fix — a node that
    # keeps missing its own probe deadlines widens its timers instead of
    # accusing others.
    soak_swim = SwimConfig(
        probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0,
        lifeguard=True,
    )

    async def boot_one(addr, bootstrap=(), cfg=None):
        cfg = cfg or fast_config(addr, bootstrap)
        agent = await setup(cfg, network=net)
        agent.membership.config = soak_swim
        agent.store.apply_schema_sql(TEST_SCHEMA)
        await run(agent)
        return agent

    names = [f"chaos-{i}" for i in range(4)]
    agents = {}
    cfgs = {}
    for i, name in enumerate(names):
        boots = tuple(rng.sample(names[:i], min(i, 2))) if i else ()
        cfgs[name] = fast_config(name, boots)
        agents[name] = await boot_one(name, cfg=cfgs[name])
    a, b, c, d = (agents[n] for n in names)

    try:
        # phase 1: concurrent writers + a multi-chunk transaction (the
        # chunked changeset forces partial-version buffering downstream,
        # firing "buffered version drained")
        for i, name in enumerate(names):
            await insert(agents[name], 100 + i, f"from-{name}")
        from corrosion_tpu.agent.run import make_broadcastable_changes

        big = "x" * 400
        await make_broadcastable_changes(
            a,
            lambda tx: [
                tx.execute(
                    "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                    (1000 + k, big),
                )
                for k in range(80)
            ],
        )
        want = len(names) + 80

        def all_converged(n_rows):
            return lambda: all(
                count_rows(ag) == n_rows for ag in agents.values()
            )

        def sync_diag() -> dict:
            """Why is a node short?  Per-agent bookie state: heads by
            origin, open gaps, incomplete partials — the difference
            between 'lost and unnoticed' and 'known-missing but never
            repaired' (r20: the rare in-suite phase-1 stall needs this
            to be attributable post-hoc)."""
            out = {}
            for name, ag in agents.items():
                rows = {}
                for aid, booked in ag.bookie.items().items():
                    with booked.read() as bv:
                        rows[str(aid)[:8]] = {
                            "head": bv.last() or 0,
                            "needed": list(bv.needed)[:4],
                            "partials": sum(
                                1 for p in bv.partials.values()
                                if not p.is_complete()
                            ),
                        }
                out[name] = rows
            return out

        # r21 load-tolerant bound for the watched phase-1 flake: the 80
        # concurrent inserts land as multi-chunk broadcasts, and under
        # full-suite load on the 1-core host the broadcast/apply queues
        # can back up long enough that the ROW COUNT (the progress
        # probe) freezes >30 s while the bookie still shows known-
        # missing-but-repairing state — the default stall window called
        # that a livelock.  Stall-clock 60 s (same discipline the later
        # phases already use) keeps the progress-based detection but
        # tolerates a queue-drain pause; cap 300 s still bounds a true
        # livelock well under the suite timeout.
        assert await wait_progress(
            all_converged(want),
            lambda: tuple(count_rows(ag) for ag in agents.values()),
            stall=60.0, cap=300.0,
        ), (
            f"phase1 rows: {[count_rows(ag) for ag in agents.values()]}\n"
            f"bookie: {sync_diag()}"
        )
        summary["phases"].append({"phase": "concurrent-writers", "rows": want})

        # phase 2: partition d from everyone; write on both sides; heal;
        # anti-entropy must repair (fires "syncs with other nodes")
        for name in names[:3]:
            net.partition(name, "chaos-3")
        await insert(a, 2001, "majority-side")
        await insert(d, 2002, "minority-side")
        await asyncio.sleep(rng.uniform(0.5, 1.5))
        for name in names[:3]:
            net.heal(name, "chaos-3")
        want += 2
        assert await wait_progress(
            all_converged(want),
            lambda: tuple(count_rows(ag) for ag in agents.values()),
        ), f"post-heal rows: {[count_rows(ag) for ag in agents.values()]}"
        summary["phases"].append({"phase": "partition-heal", "rows": want})

        # phase 3: restart c from its on-disk state (checkpoint/resume:
        # bookie rebuild + member resurrection), then write more
        from corrosion_tpu.agent.run import shutdown as _shutdown

        await _shutdown(c)
        agents["chaos-2"] = c = await boot_one("chaos-2", cfg=cfgs["chaos-2"])
        await insert(b, 3001, "post-restart")
        want += 1
        assert await wait_progress(
            all_converged(want),
            lambda: tuple(count_rows(ag) for ag in agents.values()),
        ), f"post-restart rows: {[count_rows(ag) for ag in agents.values()]}"
        summary["phases"].append({"phase": "agent-restart", "rows": want})

        # phase 4: permanent crash of d — the others must down it via
        # their own SWIM pipeline, with no other member downed (FP 0)
        net.take_down("chaos-3")
        await shutdown(d)
        agents.pop("chaos-3")
        d_id = d.actor.id

        assert await wait_progress(
            lambda: all(
                d_id in ag.membership.downed for ag in agents.values()
            ),
            lambda: tuple(
                (len(ag.membership.downed), ag.membership._probe_no)
                for ag in agents.values()
            ),
            stall=60.0, cap=300.0,
        ), "crash of chaos-3 never detected cluster-wide"
        # Load-tolerant FP bound (r12, the r11 full-suite flake): a
        # descheduled host can still wrongfully down a live member for a
        # beat, but SWIM guarantees RECOVERY — the victim refutes with a
        # bumped incarnation and the ALIVE assertion pops it from
        # `downed`.  So the invariant asserted is "no PERSISTENT false
        # positive": transient FP downs are waited out (and reported),
        # only ones that never heal fail the soak.
        live_ids = {ag.actor.id for ag in agents.values()}

        def fp_downs():
            return {
                name: sorted(
                    str(aid)
                    for aid in (set(ag.membership.downed) - {d_id})
                    & live_ids
                )
                for name, ag in agents.items()
                if (set(ag.membership.downed) - {d_id}) & live_ids
            }

        transient_fp = fp_downs()
        assert await wait_progress(
            lambda: not fp_downs(),
            fp_downs,
            stall=60.0, cap=300.0,
        ), f"persistent false-positive downs: {fp_downs()}"
        summary["phases"].append(
            {
                "phase": "crash-detection",
                "downed": 1,
                "transient_fp_downs": sum(
                    len(v) for v in transient_fp.values()
                ),
            }
        )

        # replication still flows after all of it
        await insert(a, 4001, "after-chaos")
        want += 1
        assert await wait_progress(
            lambda: all(count_rows(ag) == want for ag in agents.values()),
            lambda: tuple(count_rows(ag) for ag in agents.values()),
        )
        summary["phases"].append({"phase": "post-chaos-write", "rows": want})

        # phase 6 (r17): a COLD node joins after every write happened —
        # its whole table can only arrive through the pull plane (no
        # broadcast carries old rows), so the 'syncs with other nodes'
        # coverage fires DETERMINISTICALLY here instead of racing the
        # broadcast backlog for the single partition-repair row (the
        # pre-r17 soak's one organic sync window, which full-suite load
        # could let the backlog win — the r16/r17 in-suite flake)
        agents["chaos-cold"] = cold = await boot_one(
            "chaos-cold", bootstrap=tuple(rng.sample(names[:3], 2))
        )
        assert await wait_progress(
            lambda: count_rows(cold) == want,
            lambda: (count_rows(cold), cold.membership.cluster_size,
                     cold.membership._probe_no),
            stall=60.0, cap=300.0,
        ), f"cold join stalled at {count_rows(cold)}/{want}"
        summary["phases"].append({"phase": "cold-join-catchup", "rows": want})
    finally:
        from corrosion_tpu.agent.run import shutdown as _sd

        for ag in agents.values():
            await _sd(ag)

    fired = invariants.sometimes_registry()
    summary["sometimes"] = dict(fired)
    missing = EXPECTED_SOMETIMES - set(fired)
    assert not missing, f"coverage contract unmet, never fired: {missing}"
    return summary


def test_flaky_node_ab_banked_record_holds_acceptance():
    """Tier-1 replay guard on the banked flaky-node A/B (r9): the
    record in CHAOS_SOAK.json must keep satisfying the acceptance
    inequalities — >= 2 seeds, >= 5x collapse of ground-truth
    false-positive suspicions AND wrongful downs, real-crash detection
    within 2x vanilla, with a non-empty flight-recorder timeline.  The
    live directional replay runs in tests/test_lifeguard.py (tiny
    shapes, both kernels); this pins the banked artifact against drift
    (`scripts/chaos_soak.py --phase flaky-node` re-banks it)."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "CHAOS_SOAK.json")
    with open(path) as f:
        record = json.load(f)
    fl = record["flaky_node"]
    runs = fl["runs"]
    assert len(runs) >= 2, "flaky-node A/B needs >= 2 seeds"
    assert len({r["seed"] for r in runs}) == len(runs)
    for r in runs:
        v, lf = r["vanilla"], r["lifeguard"]
        assert v["suspect_fp"] >= 5 * max(1, lf["suspect_fp"]), r
        assert v["down_fp"] >= 5 * max(1, lf["down_fp"]), r
        assert v["detect_ticks"] and lf["detect_ticks"], r
        assert lf["detect_ticks"] <= 2 * v["detect_ticks"], r
        assert lf["timeline"], "missing flight timeline"
        assert lf["lhm_degraded"] >= 1, "LHA-Probe never engaged"


def test_chaos_soak_strict_invariants(monkeypatch):
    monkeypatch.setenv("CORRO_INVARIANTS", "strict")
    # outer bound must exceed the inner wait_progress livelock cap
    # (900 s) so a stall surfaces as the phase's diagnostic assertion,
    # not a bare TimeoutError with no context
    summary = asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(run_soak(seed=1337), 1200)
    )
    assert len(summary["phases"]) == 6

"""r11 SLO latency plane (runtime/latency.py + the corro.e2e.* hop
stamps): percentile correctness against a sorted-array oracle at bucket
resolution, window expiry/merge, cross-node clock-skew clamping, the
SloMonitor breach tracker, Prometheus exposition of the windowed
instruments, and a tiny-shape two-agent e2e round trip that proves all
five write→event stages observe a sample.
"""

import asyncio
import math
import random

import pytest

from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime import latency as lat
from corrosion_tpu.runtime.metrics import Registry


# -- histogram core ---------------------------------------------------------


def test_percentiles_match_sorted_array_oracle():
    rng = random.Random(5)
    samples = [rng.lognormvariate(-6.0, 2.0) for _ in range(5000)]
    h = lat.LatencyHistogram()
    for s in samples:
        h.observe(s)
    assert h.count == len(samples)
    ordered = sorted(samples)
    for q in lat.QUANTILES:
        oracle = ordered[max(0, math.ceil(q * len(samples)) - 1)]
        got = h.quantile(q)
        # the reported value is the oracle's bucket upper edge: never
        # below the true sample, at most one ~5 % bucket above (small
        # float fuzz allowed at the bucket boundary)
        assert oracle * 0.999 <= got <= oracle * lat.RATIO * 1.001, (
            q,
            oracle,
            got,
        )


def test_merge_equals_concatenation():
    rng = random.Random(7)
    a_samples = [rng.expovariate(100.0) for _ in range(700)]
    b_samples = [rng.expovariate(5.0) for _ in range(300)]
    a, b, both = (
        lat.LatencyHistogram(),
        lat.LatencyHistogram(),
        lat.LatencyHistogram(),
    )
    for s in a_samples:
        a.observe(s)
        both.observe(s)
    for s in b_samples:
        b.observe(s)
        both.observe(s)
    a.merge(b)
    assert a.count == both.count
    assert a.total == pytest.approx(both.total)
    assert a.nonzero_buckets() == both.nonzero_buckets()
    for q in lat.QUANTILES:
        assert a.quantile(q) == both.quantile(q)


def test_diff_isolates_interval():
    h = lat.LatencyHistogram()
    for _ in range(10):
        h.observe(0.001)
    before = h.copy()
    for _ in range(5):
        h.observe(1.0)
    d = h.diff(before)
    assert d.count == 5
    assert d.quantile(0.5) == pytest.approx(lat.bucket_upper(lat.bucket_index(1.0)))


def test_quantile_empty_and_extremes():
    h = lat.LatencyHistogram()
    assert h.quantile(0.99) is None
    h.observe(0.0)  # below BASE → bucket 0
    h.observe(1e9)  # beyond the span → last bucket
    assert h.quantile(0.5) == lat.bucket_upper(0)
    assert h.quantile(0.999) == lat.bucket_upper(lat.N_BUCKETS - 1)


def test_count_le_bucket_resolution():
    h = lat.LatencyHistogram()
    for v in (0.001, 0.010, 0.100, 1.0):
        h.observe(v)
    assert h.count_le(0.5) == 3
    assert h.count_le(2.0) == 4
    assert h.count_le(1e-7) == 0


# -- sliding window ---------------------------------------------------------


def test_window_expiry_and_cumulative():
    t = [0.0]
    w = lat.WindowedLatency(slot_secs=1.0, slots=4, clock=lambda: t[0])
    w.observe(0.010)  # epoch 0
    t[0] = 1.5
    w.observe(0.020)  # epoch 1
    q = w.quantiles(window_secs=10.0)  # capped at 4 s ring coverage
    assert q["count"] == 2
    # advance until epoch 0's slot no longer overlaps the window
    # (slot-granular: a slot counts while ANY part of it is inside);
    # the cumulative histogram keeps both samples forever
    t[0] = 5.1
    assert w.window_hist(10.0).count == 1
    assert w.snapshot_cumulative().count == 2
    # a small window can exclude even recent slots
    t[0] = 1.9
    assert w.window_hist(0.5).count == 1  # 60 ms-old epoch-1 slot only


def test_window_slot_reuse_resets_expired_data():
    t = [0.0]
    w = lat.WindowedLatency(slot_secs=1.0, slots=2, clock=lambda: t[0])
    for _ in range(50):
        w.observe(0.001)  # epoch 0
    t[0] = 2.1  # epoch 2 → same ring index as epoch 0
    w.observe(0.5)
    h = w.window_hist(1.0)
    assert h.count == 1  # the 50 old samples did not leak into the slot
    assert w.snapshot_cumulative().count == 51


# -- hop stamps -------------------------------------------------------------


def test_skew_negative_delta_clamped_and_counted():
    reg = Registry()
    v = lat.e2e_observe("apply", -0.5, registry=reg, source="sync")
    assert v == 0.0
    assert (
        reg.counter("corro.e2e.skew.clamped.total", stage="apply").value == 1
    )
    h = lat.stage_hists(registry=reg)["apply"]
    assert h.count == 1
    assert h.quantile(0.5) == lat.bucket_upper(0)  # clamped into bucket 0
    # positive deltas pass through unclamped
    assert lat.e2e_observe("apply", 0.25, registry=reg) == 0.25
    assert (
        reg.counter("corro.e2e.skew.clamped.total", stage="apply").value == 1
    )


def test_stage_hists_merge_across_label_sets():
    reg = Registry()
    lat.e2e_observe("apply", 0.001, registry=reg, source="broadcast")
    lat.e2e_observe("apply", 0.002, registry=reg, source="sync")
    lat.e2e_observe("match", 0.003, registry=reg)
    h = lat.stage_hists(registry=reg)
    assert h["apply"].count == 2
    assert h["match"].count == 1
    assert h["deliver"].count == 0


def test_batch_stamp_oldest_wins():
    a = lat.BatchStamp(origin=100.0, applied=105.0)
    b = lat.BatchStamp(origin=99.0, applied=106.0)
    c = a.oldest(b)
    assert (c.origin, c.applied) == (99.0, 105.0)
    # None origins never mask a real stamp
    d = lat.BatchStamp(origin=None, applied=104.0).oldest(a)
    assert (d.origin, d.applied) == (100.0, 104.0)
    assert a.oldest(None) is a


def test_stage_report_snapshot_diff():
    reg = Registry()
    lat.e2e_observe("deliver", 0.010, registry=reg)
    before = lat.snapshot_stages(registry=reg)
    lat.e2e_observe("deliver", 0.020, registry=reg)
    lat.e2e_observe("deliver", 0.030, registry=reg)
    rep = lat.stage_report(before=before, registry=reg)
    assert rep["deliver"]["count"] == 2  # the pre-snapshot sample is out
    assert rep["broadcast"]["count"] == 0
    assert rep["deliver"]["mean"] == pytest.approx(0.025, rel=0.2)


# -- SLO monitor ------------------------------------------------------------


def test_slo_monitor_burn_and_sustained_breach(tmp_path, monkeypatch):
    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    reg = Registry()
    mon = lat.SloMonitor(
        targets={"deliver": 0.001},
        objective=0.99,
        breach_checks=2,
        registry=reg,
    )
    # all samples violate the 1 ms target → burn far above 1
    for _ in range(10):
        lat.e2e_observe("deliver", 0.5, registry=reg)
    r1 = mon.check()
    assert r1["deliver"]["breached"]
    assert r1["deliver"]["burn_rate"] > 1.0
    assert r1["deliver"]["target"] == 0.001
    # stages without a target are reported but never judged
    assert r1["apply"]["target"] is None
    assert not r1["apply"]["breached"]
    assert reg.counter("corro.slo.incidents.total", stage="deliver").value == 0
    r2 = mon.check()
    assert r2["deliver"]["breached"]
    # the sustained breach fired exactly ONE incident per episode
    assert reg.counter("corro.slo.incidents.total", stage="deliver").value == 1
    mon.check()
    assert reg.counter("corro.slo.incidents.total", stage="deliver").value == 1
    dumps = list(tmp_path.glob("flight_incident_*slo_breach_deliver*"))
    assert dumps, "sustained breach must trip a flight-recorder dump"


def test_slo_monitor_within_objective_no_breach():
    reg = Registry()
    mon = lat.SloMonitor(
        targets={"deliver": 1.0}, objective=0.5, registry=reg
    )
    for _ in range(8):
        lat.e2e_observe("deliver", 0.001, registry=reg)
    lat.e2e_observe("deliver", 5.0, registry=reg)  # 1 of 9 over: 11 % < 50 %
    r = mon.check()
    assert not r["deliver"]["breached"]
    assert 0.0 < r["deliver"]["burn_rate"] < 1.0


# -- exposition -------------------------------------------------------------


def test_prometheus_exposition_of_latency_series():
    reg = Registry()
    w = reg.latency("corro.e2e.deliver.seconds")
    for i in range(1, 101):
        w.observe(0.0005 * i)
    text = reg.render_prometheus()
    assert 'corro_e2e_deliver_seconds_bucket{le="+Inf"} 100' in text
    assert "corro_e2e_deliver_seconds_sum" in text
    assert "corro_e2e_deliver_seconds_count 100" in text
    assert 'quantile="0.99"' in text
    # cumulative bucket counts are monotone and end at the total
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("corro_e2e_deliver_seconds_bucket")
    ]
    assert cums == sorted(cums) and cums[-1] == 100
    # snapshot() exposes the cumulative count/sum rows for /v1/status
    rows = {
        name: v
        for _k, name, _l, v in reg.snapshot()
        if name.startswith("corro.e2e.")
    }
    assert rows["corro.e2e.deliver.seconds_count"] == 100


# -- end-to-end: all five stages observe one write→event round trip ---------


def test_e2e_stages_observe_one_cross_node_roundtrip():
    from tests.test_agent import insert, wait_until
    from tests.test_http_api import boot_with_api
    from tests.test_pubsub_http import next_of

    async def main():
        net = MemNetwork(seed=61)
        a, api_a, client_a = await boot_with_api(net, "agent-a")
        b, api_b, client_b = await boot_with_api(net, "agent-b", ["agent-a"])
        try:
            await wait_until(
                lambda: len(a.members) == 1 and len(b.members) == 1
            )
            stream = client_b.subscribe("SELECT id, text FROM tests")
            it = stream.__aiter__()
            await next_of(it, "eoq")

            before = lat.snapshot_stages()
            await insert(a, 42, "stamped")
            ev = await next_of(it, "change", timeout=15.0)
            assert ev["change"][2] == [42, "stamped"]

            # the event reached the client, so every hop has run; the
            # deliver/total observations land right after the stream
            # write — wait a beat for them
            def all_stages_sampled():
                rep = lat.stage_report(before=before)
                return all(
                    rep[s]["count"] >= 1 for s in lat.E2E_STAGES
                )

            assert await wait_until(all_stages_sampled, timeout=10.0), (
                lat.stage_report(before=before)
            )
            rep = lat.stage_report(before=before)
            for s in lat.E2E_STAGES:
                assert rep[s]["p99"] is not None
            # the GET /v1/slo plane serves the same stages
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{api_b.addrs[0]}/v1/slo"
                ) as resp:
                    assert resp.status == 200
                    body = await resp.json()
            assert set(body["stages"]) == set(lat.E2E_STAGES)
            assert body["stages"]["total"]["cumulative"]["count"] >= 1
        finally:
            await client_a.close()
            await client_b.close()
            await api_a.stop()
            await api_b.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)
            await shutdown(b)

    asyncio.run(main())


def test_agent_restart_survives_persisted_canary_table(tmp_path):
    """Regression (found driving the real CLI agent): the canary table
    persists in the db but never appears in the user's schema files, so
    an agent RESTART used to be refused by the declarative schema diff
    as a destructive `corro_canary` drop.  setup() must carry a
    persisted canary table through the configured-schema re-apply."""
    from corrosion_tpu.agent.run import setup, shutdown
    from corrosion_tpu.runtime.config import Config

    async def main():
        schema = tmp_path / "schema.sql"
        schema.write_text(
            "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
        )

        def cfg(addr):
            c = Config()
            c.db.path = str(tmp_path / "canary-restart.db")
            c.db.schema_paths = [str(schema)]
            c.gossip.bind_addr = addr
            return c

        net = MemNetwork(seed=77)
        a = await setup(cfg("restart-a"), network=net)
        # simulate a past canary run: the probe's additive table apply
        table = a.config.slo.canary_table
        parts = [
            t.raw_sql.rstrip(";") + ";"
            for t in a.store.schema.tables.values()
        ]
        parts.append(
            f'CREATE TABLE "{table}" (src TEXT NOT NULL PRIMARY KEY,'
            " n INTEGER, wall REAL);"
        )
        a.store.apply_schema_sql("\n".join(parts))
        await shutdown(a)

        # restart over the same db with the ORIGINAL schema files
        b = await setup(cfg("restart-b"), network=net)
        assert table in b.store.schema.tables
        assert "tests" in b.store.schema.tables
        await shutdown(b)

    asyncio.run(main())


def test_canary_probe_measures_local_roundtrip():
    """Opt-in canary: one agent, canary enabled — the loop must create
    its table through the additive schema re-apply, write through the
    real write path, see the event on its self-subscription, and record
    a corro.e2e.canary{scope=local} sample without clobbering the user
    schema."""
    from tests.test_agent import boot, wait_until
    from corrosion_tpu.runtime.metrics import METRICS

    async def main():
        net = MemNetwork(seed=62)
        a = await boot(net, "agent-canary")
        try:
            a.config.slo.canary = True
            a.config.slo.canary_interval_secs = 0.2
            from corrosion_tpu.agent.run import canary_loop

            task = asyncio.ensure_future(canary_loop(a))
            inst = METRICS.latency(
                "corro.e2e.canary.seconds", scope="local"
            )
            before = inst.snapshot_cumulative().count

            def canary_observed():
                return inst.snapshot_cumulative().count > before

            assert await wait_until(canary_observed, timeout=15.0)
            # the user schema survived the additive canary table apply
            assert "tests" in a.store.schema.tables
            assert a.config.slo.canary_table in a.store.schema.tables
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        finally:
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())

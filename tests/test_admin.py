"""Admin UDS protocol tests: command dispatch end-to-end over a real unix
socket against a live agent. Mirrors `klukai/src/admin.rs` coverage."""

from corrosion_tpu.runtime.tmpdb import fresh_db_path
import asyncio
import logging

from corrosion_tpu.admin import AdminClient, AdminServer
from corrosion_tpu.agent.run import make_broadcastable_changes, run, setup, shutdown
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.types.base import Timestamp

TEST_SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
)


def cfg(addr):
    c = Config()
    c.db.path = fresh_db_path()
    c.gossip.bind_addr = addr
    return c


async def boot_with_admin(tmp_path, net, addr):
    agent = await setup(cfg(addr), network=net)
    agent.store.apply_schema_sql(TEST_SCHEMA)
    await run(agent)
    sock = str(tmp_path / "admin.sock")
    server = AdminServer(agent, sock)
    await server.start()
    return agent, server, sock


async def test_ping_members_states_subs(tmp_path):
    net = MemNetwork()
    agent, server, sock = await boot_with_admin(tmp_path, net, "a:1")
    try:
        async with AdminClient(sock) as c:
            r = await c.call({"cmd": "ping"})
            assert r["ok"] and r["json"] == ["pong"]

            r = await c.call({"cmd": "cluster", "sub": "members"})
            assert r["ok"] and r["json"] == [[]]

            r = await c.call({"cmd": "cluster", "sub": "membership-states"})
            assert r["ok"]
            states = r["json"][0]
            assert states[-1]["self"] is True
            assert states[-1]["id"] == str(agent.actor_id)

            r = await c.call({"cmd": "subs", "sub": "list"})
            assert r["ok"] and r["json"] == [[]]

            r = await c.call({"cmd": "locks"})
            assert r["ok"]

            r = await c.call({"cmd": "bogus"})
            assert not r["ok"] and "unknown command" in r["error"]
    finally:
        await server.stop()
        await shutdown(agent)


async def test_sync_generate_and_actor_version(tmp_path):
    net = MemNetwork()
    agent, server, sock = await boot_with_admin(tmp_path, net, "a:1")
    try:
        await make_broadcastable_changes(
            agent,
            lambda tx: [tx.execute(
                "INSERT INTO tests (id, text) VALUES (1, 'x')"
            )],
        )
        async with AdminClient(sock) as c:
            r = await c.call({"cmd": "sync", "sub": "generate"})
            assert r["ok"]
            state = r["json"][0]
            assert state["heads"] == {str(agent.actor_id): 1}

            r = await c.call(
                {
                    "cmd": "actor",
                    "sub": "version",
                    "actor_id": str(agent.actor_id),
                    "version": 1,
                }
            )
            assert r["ok"] and r["json"][0] == {"state": "current"}

            r = await c.call(
                {
                    "cmd": "actor",
                    "sub": "version",
                    "actor_id": str(agent.actor_id),
                    "version": 99,
                }
            )
            assert r["ok"] and r["json"][0] == {"state": "unknown"}
    finally:
        await server.stop()
        await shutdown(agent)


async def test_reconcile_gaps_repairs_stale_gap(tmp_path):
    net = MemNetwork()
    agent, server, sock = await boot_with_admin(tmp_path, net, "a:1")
    try:
        await make_broadcastable_changes(
            agent,
            lambda tx: [tx.execute(
                "INSERT INTO tests (id, text) VALUES (1, 'x')"
            )],
        )
        # corrupt: claim version 1 of ourselves is a gap
        booked = agent.bookie.ensure(agent.actor_id)
        with booked.write("test") as bv:
            bv.needed.insert(1, 1)
        async with AdminClient(sock) as c:
            r = await c.call({"cmd": "sync", "sub": "reconcile-gaps"})
            assert r["ok"]
            assert r["json"][0]["actors_fixed"] == 1
        with booked.read() as bv:
            assert list(bv.needed) == []
        # idempotent
        async with AdminClient(sock) as c:
            r = await c.call({"cmd": "sync", "sub": "reconcile-gaps"})
            assert r["ok"] and r["json"][0]["actors_fixed"] == 0
    finally:
        await server.stop()
        await shutdown(agent)


async def test_cluster_rejoin_and_set_id(tmp_path):
    net = MemNetwork()
    agent, server, sock = await boot_with_admin(tmp_path, net, "a:1")
    try:
        old_bump = agent.membership.identity.bump
        async with AdminClient(sock) as c:
            r = await c.call({"cmd": "cluster", "sub": "rejoin"})
            assert r["ok"]
            assert agent.membership.identity.bump == old_bump + 1

            r = await c.call(
                {"cmd": "cluster", "sub": "set-id", "cluster_id": 7}
            )
            assert r["ok"]
            assert agent.membership.identity.cluster_id.value == 7
            assert agent.actor.cluster_id.value == 7
    finally:
        await server.stop()
        await shutdown(agent)


async def test_log_set_reset(tmp_path):
    net = MemNetwork()
    agent, server, sock = await boot_with_admin(tmp_path, net, "a:1")
    try:
        async with AdminClient(sock) as c:
            r = await c.call(
                {
                    "cmd": "log",
                    "sub": "set",
                    "filter": "corrosion_tpu.agent=DEBUG",
                }
            )
            assert r["ok"]
            assert (
                logging.getLogger("corrosion_tpu.agent").level
                == logging.DEBUG
            )
            r = await c.call({"cmd": "log", "sub": "reset"})
            assert r["ok"]
            assert (
                logging.getLogger("corrosion_tpu.agent").level
                == logging.NOTSET
            )
    finally:
        await server.stop()
        await shutdown(agent)

"""Tail-based trace capture (runtime/tracestore.py): keep/drop
decisions, bounded buffering, the kept-trace query surface, and the
span-routing seam in runtime/trace.py.

Everything here is deterministic: a fake clock drives idle-close, and
trace ids are crafted so the 1/N lottery verdict is chosen by the test
(int(trace_id[:8], 16) % lottery_n)."""

from __future__ import annotations

from corrosion_tpu.runtime import trace as tr
from corrosion_tpu.runtime import tracestore
from corrosion_tpu.runtime.tracestore import TraceStore


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _tid(prefix8: str) -> str:
    """A 32-hex trace id whose lottery draw is int(prefix8, 16)."""
    assert len(prefix8) == 8
    return prefix8 + "0" * 24


def _span(tid, stage, dur_s, *, error=False, forced=False, start_s=0.0,
          **attrs):
    start_ns = int((1_000_000 + start_s) * 1e9)
    a = {"stage": stage}
    a.update({k: str(v) for k, v in attrs.items()})
    return {
        "name": f"{stage}.span",
        "trace_id": tid,
        "span_id": "ab" * 8,
        "parent_span_id": None,
        "start_ns": start_ns,
        "end_ns": start_ns + int(dur_s * 1e9),
        "attrs": a,
        "error": error,
        "forced": forced,
    }


def _store(**kw) -> TraceStore:
    kw.setdefault("targets", {"apply": 0.5, "deliver": 0.1})
    kw.setdefault("lottery_n", 0)  # deterministic: lottery off unless set
    kw.setdefault("clock", FakeClock())
    return TraceStore(**kw)


def _close_all(st: TraceStore) -> int:
    st._clock.t += st.idle_close_secs + 1
    return st.sweep()


def test_healthy_trace_dropped_at_close():
    st = _store()
    tid = _tid("00000001")  # lottery off anyway
    st.add_span(_span(tid, "write", 0.001))
    st.add_span(_span(tid, "apply", 0.01))
    assert _close_all(st) == 1
    assert st.kept() == []
    assert st.dropped_total == 1 and st.kept_total == 0


def test_slo_breach_keeps_with_stage_reason():
    st = _store()
    tid = _tid("00000001")
    st.add_span(_span(tid, "write", 0.001))
    st.add_span(_span(tid, "apply", 0.9))  # > 0.5 target
    _close_all(st)
    (kept,) = st.kept()
    assert kept["trace_id"] == tid
    assert kept["reason"] == "slo:apply"
    assert kept["stages"]["apply"]["max_secs"] > 0.5


def test_error_and_forced_precede_slo_and_lottery():
    st = _store()
    t_err = _tid("00000001")
    st.add_span(_span(t_err, "apply", 0.9, error=True))
    t_forced = _tid("00000002")
    st.add_span(_span(t_forced, "write", 0.001, forced=True))
    _close_all(st)
    reasons = {t["trace_id"]: t["reason"] for t in st.kept(n=10)}
    assert reasons[t_err] == "error"
    assert reasons[t_forced] == "forced"


def test_lottery_is_deterministic_on_trace_id():
    st = _store(lottery_n=16)
    winner = _tid("00000010")  # 0x10 % 16 == 0
    loser = _tid("00000011")  # 0x11 % 16 == 1
    assert st.head_forced(winner) and not st.head_forced(loser)
    st.add_span(_span(winner, "write", 0.001))
    st.add_span(_span(loser, "write", 0.001))
    _close_all(st)
    kept_ids = [t["trace_id"] for t in st.kept(n=10)]
    assert kept_ids == [winner]
    assert st.kept(n=10)[0]["reason"] == "lottery"
    # lottery_n=0 disables the lottery entirely
    assert not _store(lottery_n=0).head_forced(winner)


def test_buffer_evicts_oldest_trace_whole():
    st = _store(max_traces=3)
    tids = [_tid(f"0000000{i}") for i in range(1, 5)]
    for tid in tids:
        st.add_span(_span(tid, "apply", 0.9))
    # the oldest trace was evicted whole; the 3 newest close + keep
    _close_all(st)
    kept_ids = {t["trace_id"] for t in st.kept(n=10)}
    assert kept_ids == set(tids[1:])


def test_per_trace_span_cap_counts_overflow():
    st = _store(max_spans_per_trace=4)
    tid = _tid("00000001")
    for _ in range(7):
        st.add_span(_span(tid, "apply", 0.9))
    _close_all(st)
    (kept,) = st.kept()
    assert kept["n_spans"] == 4 and kept["spans_dropped"] == 3


def test_summary_breakdown_filters_and_exemplars():
    st = _store()
    slow = _tid("00000001")
    st.add_span(_span(slow, "write", 0.002, actor="a1", table="tests"))
    st.add_span(
        _span(slow, "apply", 0.9, actor="a2", table="tests", hop=1,
              start_s=0.002)
    )
    fast = _tid("00000002")
    st.add_span(_span(fast, "apply", 0.6, actor="a9", table="other"))
    _close_all(st)

    # slowest-N ordering: `slow` spans ~0.9s total, `fast` ~0.6s
    ids = [t["trace_id"] for t in st.kept(n=10)]
    assert ids == [slow, fast]
    # filters
    assert [t["trace_id"] for t in st.kept(actor="a2")] == [slow]
    assert [t["trace_id"] for t in st.kept(table="other")] == [fast]
    assert [t["trace_id"] for t in st.kept(stage="write")] == [slow]
    # per-stage breakdown + cross-node rollup
    (kept,) = st.kept(actor="a2")
    assert set(kept["stages"]) == {"write", "apply"}
    assert kept["actors"] == ["a1", "a2"] and kept["hops"] == 1
    assert kept["spans"][0]["stage"] == "write"  # start-ordered
    # stage exemplars, slowest first
    assert st.slowest_ids("apply", 2) == [slow, fast]
    assert st.slowest_ids("write", 2) == [slow]


def test_kept_ring_bounded():
    st = _store(keep_max=2)
    for i in range(1, 5):
        tid = _tid(f"0000000{i}")
        st.add_span(_span(tid, "apply", 0.9))
        _close_all(st)
    assert st.census()["kept_ring"] == 2
    assert st.census()["kept_total"] == 4


def test_census_shape():
    st = _store()
    st.add_span(_span(_tid("00000001"), "apply", 0.9))
    c = st.census()
    assert c["enabled"] and c["buffered"] == 1
    _close_all(st)
    c2 = st.census()
    assert c2["buffered"] == 0 and c2["kept_total"] == 1


def test_kept_traces_export_to_otel_on_keep_only():
    from corrosion_tpu.runtime import otel

    class FakeExp:
        def __init__(self):
            self.spans = []

        def record(self, span):
            self.spans.append(span)

    st = _store()
    fake = FakeExp()
    otel._EXPORTER = fake
    try:
        dropped = _tid("00000001")
        st.add_span(_span(dropped, "write", 0.001))
        kept = _tid("00000002")
        st.add_span(_span(kept, "apply", 0.9))
        _close_all(st)
        assert {s["traceId"] for s in fake.spans} == {kept}
    finally:
        otel._EXPORTER = None


def test_span_routing_seam_buffers_stage_spans_only():
    """Span.__exit__ / stage_span route stage-tagged spans into the
    configured store (deferred export); untagged spans keep the r11
    direct path (tests/test_otel.py pins that side)."""
    st = tracestore.configure(
        targets={}, lottery_n=0, auto_sweep=False, clock=FakeClock()
    )
    try:
        with tr.span("write.local", stage="write", actor="a1") as sp:
            pass
        tid = sp.ctx.trace_id
        with tr.span("sync.client"):  # untagged: never buffered
            pass
        assert tid in st._buf and len(st._buf) == 1
        # stage_span synthesizes a child covering the last duration_s
        ctx = tr.stage_span(
            sp.ctx.traceparent(), "ingest.apply", "apply", 0.25,
            actor="a2", hop=1,
        )
        assert ctx.trace_id == tid
        buf = st._buf[tid]
        assert [r["attrs"]["stage"] for r in buf.spans] == ["write", "apply"]
        rec = buf.spans[1]
        assert rec["parent_span_id"] == sp.ctx.span_id
        assert abs((rec["end_ns"] - rec["start_ns"]) / 1e9 - 0.25) < 1e-6
        # unparsable / absent context: no span, no crash
        assert tr.stage_span(None, "x", "apply", 0.1) is None
        assert tr.stage_span("garbage", "x", "apply", 0.1) is None
        # unsampled wire context: context returned, nothing buffered
        unsampled = "00-" + "aa" * 16 + "-" + "bb" * 8 + "-00"
        tr.stage_span(unsampled, "x", "apply", 0.1)
        assert "aa" * 16 not in st._buf
    finally:
        tracestore.configure(None)
    assert tracestore.store() is None


def test_forced_head_decision_rides_meta_bits():
    assert tr.meta_forced(tr.make_meta(forced=True))
    assert not tr.meta_forced(tr.make_meta(forced=False, hop=5))
    assert tr.meta_hop(tr.make_meta(hop=5)) == 5
    assert tr.meta_forced(None) is False and tr.meta_hop(None) == 0

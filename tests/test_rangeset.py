"""RangeSet: coalescing, removal splitting, gaps, overlapping.

Mirrors the reference's reliance on rangemap::RangeInclusiveSet semantics
(adjacent integer ranges coalesce) in `sync.rs:126-248` and
`agent.rs:1181-1246`.
"""

import random

from corrosion_tpu.types.rangeset import RangeSet


def test_insert_coalesces_adjacent():
    rs = RangeSet()
    rs.insert(1, 2)
    rs.insert(3, 4)
    assert list(rs) == [(1, 4)]
    rs.insert(10, 12)
    assert list(rs) == [(1, 4), (10, 12)]
    rs.insert(5, 9)
    assert list(rs) == [(1, 12)]


def test_insert_overlap_merge():
    rs = RangeSet([(1, 5), (8, 10)])
    rs.insert(4, 9)
    assert list(rs) == [(1, 10)]


def test_remove_splits():
    rs = RangeSet([(1, 10)])
    rs.remove(4, 6)
    assert list(rs) == [(1, 3), (7, 10)]
    rs.remove(1, 3)
    assert list(rs) == [(7, 10)]
    rs.remove(9, 20)
    assert list(rs) == [(7, 8)]


def test_contains():
    rs = RangeSet([(5, 7), (10, 10)])
    assert rs.contains(5) and rs.contains(7) and rs.contains(10)
    assert not rs.contains(4) and not rs.contains(8) and not rs.contains(11)
    assert rs.contains_range(5, 7)
    assert not rs.contains_range(5, 10)


def test_gaps():
    rs = RangeSet([(3, 4), (8, 9)])
    assert list(rs.gaps(1, 12)) == [(1, 2), (5, 7), (10, 12)]
    assert list(rs.gaps(3, 9)) == [(5, 7)]
    assert list(RangeSet().gaps(1, 3)) == [(1, 3)]


def test_overlapping():
    rs = RangeSet([(1, 3), (5, 8), (12, 14)])
    assert list(rs.overlapping(2, 6)) == [(1, 3), (5, 8)]
    assert list(rs.overlapping(9, 11)) == []


def test_difference_union():
    a = RangeSet([(1, 10)])
    b = RangeSet([(3, 4), (8, 12)])
    assert list(a.difference(b)) == [(1, 2), (5, 7)]
    assert list(a.union(b)) == [(1, 12)]


def test_randomized_against_set_model():
    rnd = random.Random(1234)
    rs = RangeSet()
    model = set()
    for _ in range(500):
        s = rnd.randint(0, 100)
        e = s + rnd.randint(0, 10)
        if rnd.random() < 0.6:
            rs.insert(s, e)
            model |= set(range(s, e + 1))
        else:
            rs.remove(s, e)
            model -= set(range(s, e + 1))
        # full equivalence on values
        vals = {v for st, en in rs for v in range(st, en + 1)}
        assert vals == model
        # disjoint + sorted + coalesced invariants
        prev_end = None
        for st, en in rs:
            assert st <= en
            if prev_end is not None:
                assert st > prev_end + 1
            prev_end = en

"""The driver's multichip dryrun gate, kept green in CI at reduced n.

`__graft_entry__._dryrun_body` is a correctness gate (boot, 1% crash
detection, partition/heal with split-brain proof, sharded pview churn) —
this runs the identical body on the test session's 8-device virtual CPU
mesh with a smaller member count so regressions surface before the
driver runs the full n=8192 gate.
"""

import json
import os
import sys


def test_dryrun_gate_small_n(monkeypatch, capsys):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as g

    # 512 (was 1024, r16 budget audit): every gate margin holds with
    # room (boot 0.999, churn/healed 1.0, split coverage 0.499) and the
    # dense [N, N] sim work quarters — the remaining ~27 s is XLA
    # compile of the sharded step shapes, which N does not move
    monkeypatch.setenv("GRAFT_DRYRUN_N", "512")
    g._dryrun_body(8)
    out = capsys.readouterr().out
    line = next(
        ln for ln in out.splitlines() if ln.startswith("dryrun_multichip: ")
    )
    summary = json.loads(line.split(": ", 1)[1])
    assert summary["n"] == 512
    assert summary["boot"]["coverage"] >= 0.99
    assert summary["churn"]["detected"] >= 0.99
    assert summary["churn"]["false_positive"] == 0.0
    # split-brain actually formed, then healed clean
    assert summary["partitioned"]["coverage"] < 0.9
    assert summary["healed"]["coverage"] >= 0.99
    assert summary["healed"]["false_positive"] == 0.0
    assert summary["pview_churn"]["detected"] >= 0.99
    assert summary["pview_churn"]["false_positive"] == 0.0

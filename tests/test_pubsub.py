"""Live-query engine: parse, initial materialization, incremental diff,
catch-up, updates classification.

Mirrors the reference's pubsub unit coverage
(`klukai-types/src/pubsub.rs:2407+` and the subscription flows in
`api/public/pubsub.rs`), driven through the local write path so matcher
candidates arrive exactly as they do in production.
"""

import asyncio

import pytest

from corrosion_tpu.pubsub.parse import ParseError, parse_select
from corrosion_tpu.pubsub.manager import SubsManager
from corrosion_tpu.pubsub.updates import UpdatesManager
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.base import Timestamp

SCHEMA = """
CREATE TABLE users (
  id INTEGER NOT NULL PRIMARY KEY,
  name TEXT NOT NULL DEFAULT '',
  age INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE posts (
  user_id INTEGER NOT NULL,
  post_id INTEGER NOT NULL,
  title TEXT,
  PRIMARY KEY (user_id, post_id)
);
"""


def make_store():
    store = CrdtStore(":memory:")
    store.apply_schema_sql(SCHEMA)
    return store


def test_sql_hash_is_reference_seahash():
    """`corro-query-hash` wire parity (r6): the subscription hash is
    seahash over the SQL bytes, 16 lower-hex chars — exactly what a
    reference client computes from `klukai-types/src/pubsub.rs:565`
    (`seahash::hash(sql.as_bytes())` formatted `{:016x}`).  Pinned
    against the crate-vector-validated `net/seahash.py` plus one
    concrete vector so a regression to the pre-r6 truncated sha256
    (or a formatting drift) cannot pass."""
    from corrosion_tpu.net.seahash import hash_bytes
    from corrosion_tpu.pubsub.matcher import sql_hash

    sql = "SELECT id, name FROM users"
    assert sql_hash(sql) == format(hash_bytes(sql.encode("utf-8")), "016x")
    # the crate's published vector, formatted as the header value
    assert (
        format(hash_bytes(b"to be or not to be"), "016x")
        == format(1988685042348123509, "016x")
    )
    # 16 lower-hex chars, zero-padded (a u64 with leading zero nibbles
    # must not shrink the header)
    h = sql_hash(sql)
    assert len(h) == 16 and h == h.lower()


def write(store, sql, params=()):
    with store.write_tx(Timestamp(0)) as tx:
        tx.execute(sql, params)
        changes, version, last_seq = tx.commit()
    return changes


# -- parse ----------------------------------------------------------------


def test_parse_single_table():
    store = make_store()
    p = parse_select("SELECT name FROM users WHERE age > 21", store.schema)
    assert p.table_names() == ["users"]
    assert p.col_deps["users"] == {"name", "age", "id"}
    assert p.where_clause == "age > 21"


def test_parse_join_with_aliases():
    store = make_store()
    p = parse_select(
        "SELECT u.name, p.title FROM users u"
        " JOIN posts AS p ON p.user_id = u.id",
        store.schema,
    )
    assert p.table_names() == ["users", "posts"]
    assert "name" in p.col_deps["users"]
    assert "title" in p.col_deps["posts"]
    # pks always included
    assert "id" in p.col_deps["users"]
    assert {"user_id", "post_id"} <= p.col_deps["posts"]


def test_parse_star_marks_all_columns():
    store = make_store()
    p = parse_select("SELECT * FROM users", store.schema)
    assert p.col_deps["users"] == {"id", "name", "age"}


def test_parse_rejections():
    store = make_store()
    for bad in (
        "INSERT INTO users VALUES (1, 'x', 2)",
        "SELECT 1",  # no FROM
        "SELECT * FROM nope",
        "SELECT * FROM users UNION SELECT * FROM users",
        "WITH x AS (SELECT 1) SELECT * FROM x",
    ):
        with pytest.raises(ParseError):
            parse_select(bad, store.schema)


# -- matcher lifecycle ----------------------------------------------------


def run_async(coro):
    return asyncio.run(coro)


async def get_ev(q, timeout=5.0):
    """Pop ONE event from a matcher attach() queue.  Since r10 queue
    items are whole diff batches (lists of SubEvent) — one put per
    subscriber per diff — so single-event consumers buffer the rest on
    the queue object.  None / SubDead sentinels pass through bare."""
    buf = getattr(q, "_evbuf", [])
    while not buf:
        item = await asyncio.wait_for(q.get(), timeout)
        if not isinstance(item, list):
            return item
        buf = list(item)
    q._evbuf = buf[1:]
    return buf[0]


def test_initial_materialization_and_incremental():
    async def main():
        store = make_store()
        write(store, "INSERT INTO users (id, name, age) VALUES (1, 'ann', 30)")
        write(store, "INSERT INTO users (id, name, age) VALUES (2, 'bob', 17)")

        subs = SubsManager(store)
        handle, created = await subs.get_or_insert(
            "SELECT name FROM users WHERE age >= 18"
        )
        assert created
        assert handle.columns == ["name"]
        rows, _snap = handle.matcher.snapshot()
        assert [v for (_rid, v) in rows] == [["ann"]]

        q = handle.attach()

        # insert matching → insert event
        subs.match_changes(
            write(
                store,
                "INSERT INTO users (id, name, age) VALUES (3, 'cyn', 44)",
            )
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("insert", ["cyn"])

        # update matching row's projected col → update event
        subs.match_changes(
            write(store, "UPDATE users SET name = 'ann2' WHERE id = 1")
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("update", ["ann2"])

        # row falls out of the predicate → delete event
        subs.match_changes(
            write(store, "UPDATE users SET age = 10 WHERE id = 3")
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("delete", ["cyn"])

        # row enters the predicate → insert event
        subs.match_changes(
            write(store, "UPDATE users SET age = 18 WHERE id = 2")
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("insert", ["bob"])

        # real DELETE → delete event
        subs.match_changes(write(store, "DELETE FROM users WHERE id = 1"))
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("delete", ["ann2"])

        # change ids are monotonically increasing from 1
        assert handle.last_change_id == 5
        await subs.stop_all()

    run_async(main())


def test_join_subscription():
    async def main():
        store = make_store()
        write(store, "INSERT INTO users (id, name, age) VALUES (1, 'ann', 30)")
        write(
            store,
            "INSERT INTO posts (user_id, post_id, title)"
            " VALUES (1, 1, 'hello')",
        )
        subs = SubsManager(store)
        handle, created = await subs.get_or_insert(
            "SELECT u.name, p.title FROM users u"
            " JOIN posts p ON p.user_id = u.id"
        )
        rows, _snap = handle.matcher.snapshot()
        assert [v for (_r, v) in rows] == [["ann", "hello"]]
        q = handle.attach()

        # new post by the same user → insert event through the join
        subs.match_changes(
            write(
                store,
                "INSERT INTO posts (user_id, post_id, title)"
                " VALUES (1, 2, 'world')",
            )
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("insert", ["ann", "world"])

        # renaming the user updates every joined row
        subs.match_changes(
            write(store, "UPDATE users SET name = 'ANN' WHERE id = 1")
        )
        got = {}
        for _ in range(2):
            ev = await get_ev(q)
            got[tuple(ev.values)] = ev.kind
        assert got == {("ANN", "hello"): "update", ("ANN", "world"): "update"}
        await subs.stop_all()

    run_async(main())


def test_dedupe_and_catch_up():
    async def main():
        store = make_store()
        subs = SubsManager(store)
        h1, c1 = await subs.get_or_insert("SELECT name FROM users")
        h2, c2 = await subs.get_or_insert("SELECT name FROM users")
        assert c1 and not c2 and h1.id == h2.id

        subs.match_changes(
            write(store, "INSERT INTO users (id, name) VALUES (1, 'a')")
        )
        subs.match_changes(
            write(store, "INSERT INTO users (id, name) VALUES (2, 'b')")
        )
        q = h1.attach()
        ev1 = await get_ev(q)
        ev2 = await get_ev(q)
        h1.detach(q)

        # catch-up replays the log after a given change id
        evs = h1.matcher.changes_since(ev1.change_id)
        assert [e.change_id for e in evs] == [ev2.change_id]
        assert h1.matcher.changes_since(ev2.change_id) == []
        await subs.stop_all()

    run_async(main())


def test_restore_from_disk(tmp_path):
    async def main():
        db = str(tmp_path / "main.db")
        subs_path = str(tmp_path / "subs")
        store = CrdtStore(db)
        store.apply_schema_sql(SCHEMA)
        write(store, "INSERT INTO users (id, name, age) VALUES (1, 'a', 5)")

        subs = SubsManager(store, subs_path)
        handle, _ = await subs.get_or_insert("SELECT name FROM users")
        rows, _snap = handle.matcher.snapshot()
        sub_id = handle.id
        assert len(rows) == 1
        await subs.stop_all()

        # writes land while no matcher is running: one insert, one delete
        write(store, "INSERT INTO users (id, name, age) VALUES (2, 'late', 9)")
        write(store, "DELETE FROM users WHERE id = 1")

        subs2 = SubsManager(store, subs_path)
        n = await subs2.restore()
        assert n == 1
        h = subs2.get(sub_id)
        assert h is not None and h.columns == ["name"]
        q = h.attach()
        # the restore resync sweep must surface both the missed insert
        # AND the missed delete (reference: match_changes_from_db_version)
        got = {}
        for _ in range(2):
            ev = await get_ev(q)
            got[ev.values[0]] = ev.kind
        assert got == {"late": "insert", "a": "delete"}
        rows = h.matcher.all_rows()
        assert sorted(v[0] for (_r, v) in rows) == ["late"]
        await subs2.stop_all()
        store.close()

    run_async(main())


# -- updates engine -------------------------------------------------------


def test_updates_classification():
    async def main():
        store = make_store()
        mgr = UpdatesManager(store)
        handle, created = await mgr.get_or_insert("users")
        assert created
        q = handle.attach()

        mgr.match_changes(
            write(store, "INSERT INTO users (id, name) VALUES (7, 'x')")
        )
        kind, pk = await asyncio.wait_for(q.get(), 5)
        assert (kind, pk) == ("insert", [7])

        mgr.match_changes(
            write(store, "UPDATE users SET name = 'y' WHERE id = 7")
        )
        kind, pk = await asyncio.wait_for(q.get(), 5)
        assert (kind, pk) == ("update", [7])

        mgr.match_changes(write(store, "DELETE FROM users WHERE id = 7"))
        kind, pk = await asyncio.wait_for(q.get(), 5)
        assert (kind, pk) == ("delete", [7])

        # resurrect: causal length bumps to odd again → insert
        mgr.match_changes(
            write(store, "INSERT INTO users (id, name) VALUES (7, 'z')")
        )
        kind, pk = await asyncio.wait_for(q.get(), 5)
        assert (kind, pk) == ("insert", [7])

        with pytest.raises(KeyError):
            await mgr.get_or_insert("nope")
        await mgr.stop_all()

    run_async(main())


def test_updates_delete_then_reinsert_same_batch():
    """A delete (cl=2) and re-insert (cl=3) landing in the same 600 ms
    window must resolve to the later causal length: insert, not delete."""

    async def main():
        store = make_store()
        mgr = UpdatesManager(store)
        handle, _ = await mgr.get_or_insert("users")
        write(store, "INSERT INTO users (id, name) VALUES (1, 'a')")

        q = handle.attach()
        deleted = write(store, "DELETE FROM users WHERE id = 1")
        reinserted = write(store, "INSERT INTO users (id, name) VALUES (1, 'b')")
        # both classified before the batch flushes
        mgr.match_changes(deleted + reinserted)
        kind, pk = await asyncio.wait_for(q.get(), 5)
        assert (kind, pk) == ("insert", [1])
        await mgr.stop_all()

    run_async(main())


def test_expand_sql_token_level():
    from corrosion_tpu.api.types import parse_statement
    from corrosion_tpu.api.pubsub_http import expand_sql
    from corrosion_tpu.pubsub.parse import ParseError as PE

    # prefix-colliding named params
    s = parse_statement(
        ["SELECT * FROM t WHERE x = :a AND y = :ab", {"a": 1, "ab": 2}]
    )
    out = expand_sql(s)
    assert "x = 1" in out and "y = 2" in out

    # placeholder-looking text inside a string literal is untouched
    s = parse_statement(["SELECT * FROM t WHERE x = ? AND y = ':a ?'", [5]])
    out = expand_sql(s)
    assert "x = 5" in out and "':a ?'" in out

    s = parse_statement(["SELECT * FROM t WHERE x = ?", [1, 2]])
    with pytest.raises(PE):
        expand_sql(s)


def test_expand_sql_numbered_placeholders():
    """sqlite ?N semantics: ?N binds params[N-1]; bare ? continues past
    the largest index assigned so far."""
    from corrosion_tpu.api.types import parse_statement
    from corrosion_tpu.api.pubsub_http import expand_sql
    from corrosion_tpu.pubsub.parse import ParseError as PE

    s = parse_statement(
        ["SELECT * FROM t WHERE a = ?2 OR b = ?1", [10, 20]]
    )
    out = expand_sql(s)
    assert "a = 20" in out and "b = 10" in out

    # reuse of the same index
    s = parse_statement(["SELECT * FROM t WHERE a = ?1 OR b = ?1", [7]])
    out = expand_sql(s)
    assert out.count("7") == 2

    # mixed: bare ? after ?2 takes index 3
    s = parse_statement(
        ["SELECT * FROM t WHERE a = ?2 AND b = ?", [1, 2, 3]]
    )
    out = expand_sql(s)
    assert "a = 2" in out and "b = 3" in out

    # out-of-range index
    s = parse_statement(["SELECT * FROM t WHERE a = ?5", [1]])
    with pytest.raises(PE):
        expand_sql(s)


def test_self_join_subscription():
    """Aliased self-joins get per-ref pk columns; updates through either
    ref re-evaluate the row (regression: duplicate __corro_pk columns)."""
    async def main():
        store = make_store()
        write(store, "INSERT INTO users (id, name, age) VALUES (1, 'ann', 2)")
        write(store, "INSERT INTO users (id, name, age) VALUES (2, 'bob', 0)")

        subs = SubsManager(store)
        # pair each user with the user whose id == their age
        handle, created = await subs.get_or_insert(
            "SELECT a.name, b.name FROM users a"
            " JOIN users b ON b.id = a.age"
        )
        assert created
        rows, _snap = handle.matcher.snapshot()
        assert [v for (_rid, v) in rows] == [["ann", "bob"]]

        q = handle.attach()

        # update through the second ref (b.name)
        subs.match_changes(
            write(store, "UPDATE users SET name = 'bobby' WHERE id = 2")
        )
        evs = []
        ev = await get_ev(q)
        evs.append(ev)
        # 'bobby' row update seen via ref b; ref a row (bob, age 0) has no
        # partner so stays out
        kinds = {(e.kind, tuple(e.values)) for e in evs}
        assert ("update", ("ann", "bobby")) in kinds

        # break the join → delete
        subs.match_changes(
            write(store, "UPDATE users SET age = 99 WHERE id = 1")
        )
        ev = await get_ev(q)
        assert (ev.kind, ev.values) == ("delete", ["ann", "bobby"])
        await subs.stop_all()

    run_async(main())


def test_left_join_null_extension_diffs():
    """LEFT JOIN incremental correctness: a right-side change replaces the
    NULL-extended row (partner appears) and resurrects it (last partner
    vanishes) — regression for the temp-predicate NULL hole."""
    async def main():
        store = make_store()
        write(store, "INSERT INTO users (id, name, age) VALUES (1, 'ann', 1)")

        subs = SubsManager(store)
        handle, _ = await subs.get_or_insert(
            "SELECT u.name, p.title FROM users u"
            " LEFT JOIN posts p ON p.user_id = u.id"
        )
        rows, _snap = handle.matcher.snapshot()
        assert [v for (_r, v) in rows] == [["ann", None]]
        q = handle.attach()

        # partner appears → ('ann', NULL) must go, ('ann', 'T') must come
        subs.match_changes(
            write(
                store,
                "INSERT INTO posts (user_id, post_id, title)"
                " VALUES (1, 1, 'T')",
            )
        )
        got = {}
        for _ in range(2):
            ev = await get_ev(q)
            got[(ev.kind, tuple(ev.values))] = True
        assert ("insert", ("ann", "T")) in got
        assert ("delete", ("ann", None)) in got
        rows, _ = handle.matcher.snapshot()
        assert [v for (_r, v) in rows] == [["ann", "T"]]

        # last partner vanishes → NULL-extended row resurrects
        subs.match_changes(
            write(store, "DELETE FROM posts WHERE user_id = 1")
        )
        got = {}
        for _ in range(2):
            ev = await get_ev(q)
            got[(ev.kind, tuple(ev.values))] = True
        assert ("delete", ("ann", "T")) in got
        assert ("insert", ("ann", None)) in got
        rows, _ = handle.matcher.snapshot()
        assert [v for (_r, v) in rows] == [["ann", None]]
        await subs.stop_all()

    run_async(main())


def test_order_by_respected_limit_group_by_rejected():
    async def main():
        store = make_store()
        for i, (n, a) in enumerate([("c", 30), ("a", 10), ("b", 20)]):
            write(
                store,
                f"INSERT INTO users (id, name, age) VALUES ({i}, '{n}', {a})",
            )
        subs = SubsManager(store)
        handle, _ = await subs.get_or_insert(
            "SELECT name FROM users ORDER BY age DESC"
        )
        rows, _ = handle.matcher.snapshot()
        assert [v[0] for (_r, v) in rows] == ["c", "b", "a"]
        await subs.stop_all()

        for bad in (
            "SELECT name FROM users LIMIT 1",
            "SELECT age, count(*) FROM users GROUP BY age",
            "SELECT name FROM users ORDER BY age LIMIT 2",
        ):
            with pytest.raises(ParseError):
                parse_select(bad, store.schema)

    run_async(main())


def test_expand_sql_at_dollar_named_params():
    from corrosion_tpu.api.types import parse_statement
    from corrosion_tpu.api.pubsub_http import expand_sql

    s = parse_statement(
        ["SELECT * FROM t WHERE a = @x AND b = $y AND c = :z",
         {"x": 1, "y": 2, "z": 3}]
    )
    out = expand_sql(s)
    assert "a = 1" in out and "b = 2" in out and "c = 3" in out

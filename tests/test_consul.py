"""Consul sync tests: fake Consul agent HTTP server + live corrosion API.
Mirrors `klukai/src/command/consul/sync.rs` coverage: hash-based change
detection, upsert/delete flow, notes hash directives, restart warm-up."""

from corrosion_tpu.runtime.tmpdb import fresh_db_path
import asyncio
import json

import pytest
from aiohttp import web

from corrosion_tpu.agent.run import run, setup as agent_setup, shutdown
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.client import CorrosionApiClient
from corrosion_tpu.consul import (
    AgentCheck,
    AgentService,
    ConsulClient,
    ConsulSetupError,
    ConsulSync,
    derive_ttl_status,
    diff_checks,
    diff_services,
    hash_check,
    hash_service,
    setup as consul_setup,
)
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime.config import Config

CONSUL_SCHEMA = """
CREATE TABLE consul_services (
    node TEXT NOT NULL, id TEXT NOT NULL,
    name TEXT NOT NULL DEFAULT '', tags TEXT NOT NULL DEFAULT '[]',
    meta TEXT NOT NULL DEFAULT '{}', port INTEGER NOT NULL DEFAULT 0,
    address TEXT NOT NULL DEFAULT '', updated_at INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (node, id)
);
CREATE TABLE consul_checks (
    node TEXT NOT NULL, id TEXT NOT NULL,
    service_id TEXT NOT NULL DEFAULT '', service_name TEXT NOT NULL DEFAULT '',
    name TEXT NOT NULL DEFAULT '', status TEXT NOT NULL DEFAULT '',
    output TEXT NOT NULL DEFAULT '', updated_at INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (node, id)
);
"""


class FakeConsul:
    """Stands in for the local Consul agent HTTP API."""

    def __init__(self):
        self.services = {}
        self.checks = {}
        self.ttl_updates = []  # (check_id, {"Status":…, "Output":…}) PUTs
        self.runner = None
        self.addr = None

    async def start(self):
        app = web.Application()
        app.router.add_get("/v1/agent/services", self.h_services)
        app.router.add_get("/v1/agent/checks", self.h_checks)
        app.router.add_put(
            "/v1/agent/check/update/{cid}", self.h_check_update
        )
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        host, port = self.runner.addresses[0][:2]
        self.addr = f"{host}:{port}"

    async def stop(self):
        if self.runner:
            await self.runner.cleanup()

    async def h_services(self, _req):
        return web.json_response(self.services)

    async def h_checks(self, _req):
        return web.json_response(self.checks)

    async def h_check_update(self, req):
        body = await req.json()
        if body.get("Status") not in ("passing", "warning", "critical"):
            return web.json_response({"error": "bad status"}, status=400)
        self.ttl_updates.append((req.match_info["cid"], body))
        return web.json_response({})


def svc_json(sid, name, port=80, tags=(), addr="10.0.0.1"):
    return {
        "ID": sid,
        "Service": name,
        "Tags": list(tags),
        "Meta": {},
        "Port": port,
        "Address": addr,
    }


def check_json(cid, sid, sname, status, output="", notes=""):
    return {
        "CheckID": cid,
        "Name": cid,
        "Status": status,
        "Output": output,
        "ServiceID": sid,
        "ServiceName": sname,
        "Notes": notes,
    }


def test_hash_service_stable_and_sensitive():
    a = AgentService.from_json(svc_json("s1", "web"))
    b = AgentService.from_json(svc_json("s1", "web"))
    c = AgentService.from_json(svc_json("s1", "web", port=81))
    assert hash_service(a) == hash_service(b)
    assert hash_service(a) != hash_service(c)


def test_hash_check_default_ignores_output():
    a = AgentCheck.from_json(check_json("c1", "s1", "web", "passing", "x"))
    b = AgentCheck.from_json(check_json("c1", "s1", "web", "passing", "y"))
    c = AgentCheck.from_json(check_json("c1", "s1", "web", "critical", "y"))
    assert hash_check(a) == hash_check(b)  # output not hashed by default
    assert hash_check(a) != hash_check(c)  # status is


def test_hash_check_notes_directive():
    notes = json.dumps({"hash_include": ["output"]})
    a = AgentCheck.from_json(
        check_json("c1", "s1", "web", "passing", "x", notes)
    )
    b = AgentCheck.from_json(
        check_json("c1", "s1", "web", "passing", "y", notes)
    )
    c = AgentCheck.from_json(
        check_json("c1", "s1", "web", "critical", "x", notes)
    )
    assert hash_check(a) != hash_check(b)  # output IS hashed
    assert hash_check(a) == hash_check(c)  # status is NOT


def test_diff_services_upsert_delete_unchanged():
    s1 = AgentService.from_json(svc_json("s1", "web"))
    s2 = AgentService.from_json(svc_json("s2", "db"))
    hashes = {"s1": hash_service(s1), "gone": 123}
    ups, dels = diff_services({"s1": s1, "s2": s2}, hashes)
    assert [u[0].id for u in ups] == ["s2"]  # s1 unchanged, s2 new
    assert dels == ["gone"]


async def boot(tmp_path):
    cfg = Config()
    cfg.db.path = fresh_db_path()
    cfg.gossip.bind_addr = "a:1"
    cfg.api.bind_addr = ["127.0.0.1:0"]
    net = MemNetwork()
    agent = await agent_setup(cfg, network=net)
    agent.store.apply_schema_sql(CONSUL_SCHEMA)
    await run(agent)
    api_srv = ApiServer(agent)
    await api_srv.start()
    return agent, api_srv


async def test_end_to_end_sync_flow(tmp_path):
    agent, api_srv = await boot(tmp_path)
    fake = FakeConsul()
    await fake.start()
    api = CorrosionApiClient(api_srv.addrs[0])
    consul = ConsulClient(fake.addr)
    try:
        sync = ConsulSync(consul, api, node="testnode")
        await consul_setup(api)
        await sync.load_hashes()

        # round 1: one service + one check appear
        fake.services["s1"] = svc_json("s1", "web", tags=("prod",))
        fake.checks["c1"] = check_json("c1", "s1", "web", "passing")
        svc_stats, chk_stats = await sync.tick()
        assert (svc_stats.upserted, svc_stats.deleted) == (1, 0)
        assert (chk_stats.upserted, chk_stats.deleted) == (1, 0)

        rows = await api.query_rows(
            "SELECT node, id, name, tags FROM consul_services"
        )
        assert rows == [["testnode", "s1", "web", '["prod"]']]
        rows = await api.query_rows(
            "SELECT id, status FROM consul_checks"
        )
        assert rows == [["c1", "passing"]]

        # round 2: nothing changed → no writes
        svc_stats, chk_stats = await sync.tick()
        assert svc_stats.is_zero and chk_stats.is_zero

        # round 3: status flaps, service unchanged
        fake.checks["c1"] = check_json("c1", "s1", "web", "critical")
        svc_stats, chk_stats = await sync.tick()
        assert svc_stats.is_zero
        assert chk_stats.upserted == 1
        rows = await api.query_rows("SELECT status FROM consul_checks")
        assert rows == [["critical"]]

        # round 4: service deregisters
        del fake.services["s1"]
        del fake.checks["c1"]
        svc_stats, chk_stats = await sync.tick()
        assert svc_stats.deleted == 1 and chk_stats.deleted == 1
        assert await api.query_rows("SELECT id FROM consul_services") == []

        # restart warm-up: fresh sync from the same db sees no changes
        fake.services["s2"] = svc_json("s2", "cache")
        await sync.tick()
        sync2 = ConsulSync(ConsulClient(fake.addr), api, node="testnode")
        await sync2.load_hashes()
        assert sync2.service_hashes == sync.service_hashes
        svc_stats, _ = await sync2.tick()
        assert svc_stats.is_zero
        await sync2.consul.close()
    finally:
        await consul.close()
        await api.close()
        await fake.stop()
        await api_srv.stop()
        await shutdown(agent)


def test_derive_ttl_status():
    assert derive_ttl_status([]) == ("critical", "query returned no rows")
    assert derive_ttl_status([["passing", "all good"]]) == (
        "passing", "all good",
    )
    assert derive_ttl_status([["warning"]]) == ("warning", "")
    assert derive_ttl_status([[1]]) == ("passing", "")
    assert derive_ttl_status([[0]])[0] == "critical"


async def test_reverse_ttl_sync_flow(tmp_path):
    """Store state drives TTL check PUTs back into the Consul agent,
    hash-gated on (status, output) with a forced refresh inside the TTL
    window."""
    agent, api_srv = await boot(tmp_path)
    fake = FakeConsul()
    await fake.start()
    api = CorrosionApiClient(api_srv.addrs[0])
    consul = ConsulClient(fake.addr)
    try:
        sync = ConsulSync(
            consul,
            api,
            node="testnode",
            ttl_checks=[
                {
                    "id": "corrosion-live",
                    "query": (
                        "SELECT CASE WHEN count(*) > 0 THEN 'passing'"
                        " ELSE 'critical' END, 'services=' || count(*)"
                        " FROM consul_services"
                    ),
                }
            ],
            ttl_refresh=3600.0,
        )
        await consul_setup(api)
        await sync.load_hashes()

        # round 1: empty store → critical PUT back to consul
        await sync.tick()
        assert fake.ttl_updates == [
            ("corrosion-live", {"Status": "critical", "Output": "services=0"})
        ]

        # round 2: unchanged state inside the refresh window → no new PUT
        await sync.tick()
        assert len(fake.ttl_updates) == 1

        # round 3: a service lands in the store → status flips to passing
        fake.services["s1"] = svc_json("s1", "web")
        await sync.tick()
        assert fake.ttl_updates[-1] == (
            "corrosion-live",
            {"Status": "passing", "Output": "services=1"},
        )
        assert len(fake.ttl_updates) == 2

        # round 4: refresh window elapsed → unchanged status IS re-sent
        # (Consul lapses a TTL check that is never refreshed)
        sync.ttl_refresh = 0.0
        await sync.tick()
        assert len(fake.ttl_updates) == 3
        assert fake.ttl_updates[-1][1]["Status"] == "passing"

        # a broken query degrades to a critical PUT, not an exception
        sync.ttl_checks = [
            {"id": "corrosion-live", "query": "SELECT * FROM nope"}
        ]
        sync.ttl_refresh = 3600.0
        await sync.tick()
        assert fake.ttl_updates[-1][1]["Status"] == "critical"
        assert "query failed" in fake.ttl_updates[-1][1]["Output"]
    finally:
        await consul.close()
        await api.close()
        await fake.stop()
        await api_srv.stop()
        await shutdown(agent)


async def test_setup_rejects_missing_schema(tmp_path):
    cfg = Config()
    cfg.db.path = fresh_db_path()
    cfg.gossip.bind_addr = "a:1"
    cfg.api.bind_addr = ["127.0.0.1:0"]
    net = MemNetwork()
    agent = await agent_setup(cfg, network=net)  # no consul tables
    await run(agent)
    api_srv = ApiServer(agent)
    await api_srv.start()
    api = CorrosionApiClient(api_srv.addrs[0])
    try:
        with pytest.raises(ConsulSetupError):
            await consul_setup(api)
    finally:
        await api.close()
        await api_srv.stop()
        await shutdown(agent)

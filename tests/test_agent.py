"""In-process multi-agent integration tests over the in-memory network.

Mirrors the reference's dominant test pattern
(`klukai-agent/src/agent/tests.rs`: insert_rows_and_gossip,
large_tx_sync): boot full agents, write through the public write path on
one, observe convergence on the others — via epidemic broadcast when
connected, via anti-entropy sync for late joiners.
"""

import asyncio
import socket

import pytest

from corrosion_tpu.agent.membership import SwimConfig
from corrosion_tpu.agent.run import (
    make_broadcastable_changes,
    run,
    setup,
    shutdown,
)
from corrosion_tpu.agent.syncer import parallel_sync
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.runtime.config import Config
from corrosion_tpu.runtime.tripwire import Tripwire

TEST_SCHEMA = (
    "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
)

FAST_SWIM = SwimConfig(probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0)


def free_port(dgram: bool = False) -> int:
    """Pick a currently-free loopback port.

    Inherently racy (close-then-rebind); centralized so any hardening —
    retry-on-collision, SO_REUSEADDR — lands in one place for every test
    that needs a port before the server under test binds it."""
    s = socket.socket(
        socket.AF_INET, socket.SOCK_DGRAM if dgram else socket.SOCK_STREAM
    )
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

# File-backed test dbs, NOT :memory: (runtime/tmpdb.py: the shared-cache
# in-memory fallback has no real WAL and flakes concurrent read+apply as
# "database is locked" on a loaded host). Cleaned up at interpreter exit.
from corrosion_tpu.runtime.tmpdb import fresh_db_path


def fast_config(addr: str, bootstrap=()) -> Config:
    cfg = Config()
    cfg.db.path = fresh_db_path(addr.replace(":", "_"))
    cfg.gossip.bind_addr = addr
    cfg.gossip.bootstrap = list(bootstrap)
    cfg.perf.broadcast_interval_ms = 20
    cfg.perf.apply_queue_timeout_ms = 5
    cfg.perf.sync_interval_min_secs = 0.1
    cfg.perf.sync_interval_max_secs = 0.5
    return cfg


async def boot(net, addr, bootstrap=(), cfg=None):
    agent = await setup(cfg or fast_config(addr, bootstrap), network=net)
    agent.membership.config = FAST_SWIM
    agent.store.apply_schema_sql(TEST_SCHEMA)
    await run(agent)
    return agent


async def wait_until(pred, timeout=10.0, step=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return pred()


async def wait_progress(pred, progress, stall=30.0, cap=900.0, step=0.05):
    """Wait for ``pred()``; fail only on STALL, not on wall clock.

    ``progress()`` returns any comparable snapshot (a count, a tuple);
    as long as it keeps changing, the system is making headway and the
    wait continues — a loaded 1-core host slows progress but doesn't
    stop it, which is exactly what wall-clock-coupled soak timeouts got
    wrong (r4 weak #6/#8: the coexistence soak flaked under full-suite
    load, passed in isolation).  ``stall`` bounds how long progress may
    freeze; ``cap`` is a safety net against livelock (progress changing
    forever without pred becoming true).

    The stall clock is starvation-compensated (the same correction the
    swim-parity windows apply): when a monitor wakeup arrives far past
    its ``step`` sleep, the process was descheduled — and the agents
    sharing this event loop were descheduled WITH it, so the gap is
    scheduler lag, not system silence.  Such gaps charge one step, not
    their wall duration; otherwise a single multi-second freeze of a
    loaded host trips ``stall`` the instant the monitor resumes."""
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    last = progress()
    silence = 0.0
    prev = t0
    while True:
        if pred():
            return True
        now = loop.time()
        dt, prev = now - prev, now
        silence += dt if dt <= 5 * step else step
        cur = progress()
        if cur != last:
            last, silence = cur, 0.0
        if silence > stall:
            return pred()  # stalled: one final check
        if now - t0 > cap:
            return pred()
        await asyncio.sleep(step)


def count_rows(agent, where="1=1"):
    conn = agent.store.read_conn()
    try:
        return conn.execute(
            f"SELECT COUNT(*) AS n FROM tests WHERE {where}"
        ).fetchone()["n"]
    finally:
        conn.close()


async def insert(agent, rowid, text):
    return await make_broadcastable_changes(
        agent,
        lambda tx: [
            tx.execute(
                "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                (rowid, text),
            )
        ],
    )


def test_insert_rows_and_gossip():
    async def main():
        net = MemNetwork(seed=11)
        a = await boot(net, "agent-a")
        b = await boot(net, "agent-b", bootstrap=["agent-a"])
        c = await boot(net, "agent-c", bootstrap=["agent-a"])
        try:
            assert await wait_until(
                lambda: all(
                    ag.membership.cluster_size == 3 for ag in (a, b, c)
                )
            ), [ag.membership.cluster_size for ag in (a, b, c)]

            res = await insert(a, 1, "hello")
            assert res.version == 1
            assert res.rows_affected == 1

            assert await wait_until(
                lambda: count_rows(b) == 1 and count_rows(c) == 1
            ), (count_rows(b), count_rows(c))

            # bookkeeping on the receivers records A's version
            for ag in (b, c):
                booked = ag.bookie.get(a.actor_id)
                assert booked is not None
                with booked.read() as bv:
                    assert bv.contains(1)

            # write on b propagates everywhere too
            await insert(b, 2, "world")
            assert await wait_until(
                lambda: count_rows(a) == 2 and count_rows(c) == 2
            )
        finally:
            for ag in (a, b, c):
                await shutdown(ag)

    asyncio.run(main())


def test_lww_convergence_on_conflict():
    async def main():
        net = MemNetwork(seed=13)
        a = await boot(net, "agent-a")
        b = await boot(net, "agent-b", bootstrap=["agent-a"])
        try:
            assert await wait_until(
                lambda: all(ag.membership.cluster_size == 2 for ag in (a, b))
            )
            # concurrent conflicting writes to the same row
            await asyncio.gather(
                insert(a, 7, "from-a"), insert(b, 7, "from-b")
            )

            def values():
                out = []
                for ag in (a, b):
                    conn = ag.store.read_conn()
                    try:
                        row = conn.execute(
                            "SELECT text FROM tests WHERE id = 7"
                        ).fetchone()
                        out.append(row["text"] if row else None)
                    finally:
                        conn.close()
                return out

            assert await wait_until(
                lambda: (lambda v: v[0] is not None and v[0] == v[1])(
                    values()
                )
            ), values()
        finally:
            for ag in (a, b):
                await shutdown(ag)

    asyncio.run(main())


def test_late_joiner_catches_up_via_sync():
    async def main():
        net = MemNetwork(seed=17)
        a = await boot(net, "agent-a")
        try:
            for i in range(20):
                await insert(a, i, f"row-{i}")
            assert count_rows(a) == 20

            # c joins after the writes: broadcast can't help, sync must
            c = await boot(net, "agent-c", bootstrap=["agent-a"])
            try:
                assert await wait_until(
                    lambda: c.membership.cluster_size == 2
                )
                assert await wait_until(
                    lambda: count_rows(c) == 20, timeout=15.0
                ), count_rows(c)
                booked = c.bookie.get(a.actor_id)
                with booked.read() as bv:
                    assert bv.contains_all((1, 20))
                    assert bv.last() == 20
            finally:
                await shutdown(c)
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_direct_parallel_sync_roundtrip():
    """Drive one sync session directly, no scheduler."""

    async def main():
        net = MemNetwork(seed=19)
        a = await boot(net, "agent-a")
        b = await boot(net, "agent-b")
        try:
            for i in range(5):
                await insert(a, i, f"v-{i}")
            # b knows a as a member but has no data
            b.members.add_member(a.actor)
            received = await parallel_sync(b, [a.actor])
            assert received > 0
            assert await wait_until(lambda: count_rows(b) == 5)
        finally:
            await shutdown(a)
            await shutdown(b)

    asyncio.run(main())


def test_column_change_migration_replicates_across_nodes():
    """Schema 12-step rebuild under replication (schema.rs:528-596): both
    nodes migrate a column's type with data present; writes before and
    after the migration replicate intact."""

    async def main():
        net = MemNetwork(seed=31)
        a = await boot(net, "mig-a")
        b = await boot(net, "mig-b", bootstrap=["mig-a"])
        try:
            assert await wait_until(
                lambda: all(ag.membership.cluster_size == 2 for ag in (a, b))
            )
            await insert(a, 1, "before")
            assert await wait_until(lambda: count_rows(b) == 1)

            # both nodes apply the same migration: text -> INTEGER DEFAULT 0
            migrated = (
                "CREATE TABLE tests (id INTEGER PRIMARY KEY,"
                " text INTEGER DEFAULT 0);"
            )
            a.store.apply_schema_sql(migrated)
            b.store.apply_schema_sql(migrated)
            # pre-migration data survived the rebuild on both
            for ag in (a, b):
                assert count_rows(ag) == 1

            # post-migration writes still replicate (triggers rebuilt)
            from corrosion_tpu.agent.run import make_broadcastable_changes

            await make_broadcastable_changes(
                a,
                lambda tx: [
                    tx.execute("INSERT INTO tests (id, text) VALUES (2, 7)", ())
                ],
            )
            assert await wait_until(lambda: count_rows(b) == 2), count_rows(b)
            row = b.store._conn.execute(
                "SELECT text FROM tests WHERE id = 2"
            ).fetchone()
            assert row["text"] == 7
        finally:
            for ag in (a, b):
                await shutdown(ag)

    asyncio.run(main())


def test_configurable_stress_random_topology_concurrent_writers():
    """The reference's stress-test shape (`configurable_stress_test`,
    agent/tests.rs:284, wrapped by chill/stress variants at :261-281):
    N agents on a RANDOM bootstrap topology, every agent writing
    concurrently, then full convergence — same rows everywhere, every
    writer's versions booked by every peer, membership complete, and
    zero spurious down-markings. Sized as the "chill" variant so the
    1-core CI host finishes in seconds."""
    import random

    n_agents = 6
    rows_per_agent = 5

    async def main():
        rng = random.Random(4242)
        net = MemNetwork(seed=23)
        names = [f"stress-{i}" for i in range(n_agents)]
        agents = [await boot(net, names[0])]
        for i in range(1, n_agents):
            # random topology: bootstrap via 1-2 random already-up nodes
            boots = rng.sample(names[:i], k=min(i, rng.choice((1, 2))))
            agents.append(await boot(net, names[i], bootstrap=boots))
        try:
            assert await wait_until(
                lambda: all(
                    ag.membership.cluster_size == n_agents for ag in agents
                ),
                timeout=20.0,
            ), [ag.membership.cluster_size for ag in agents]

            # every agent writes concurrently into a disjoint id range
            async def writer(ai, ag):
                for r in range(rows_per_agent):
                    await insert(
                        ag, ai * 1000 + r, f"w{ai}-r{r}"
                    )

            await asyncio.gather(
                *(writer(ai, ag) for ai, ag in enumerate(agents))
            )

            total = n_agents * rows_per_agent
            assert await wait_until(
                lambda: all(count_rows(ag) == total for ag in agents),
                timeout=30.0,
            ), [count_rows(ag) for ag in agents]

            # bookkeeping: every peer has booked every writer's versions
            def fully_booked():
                for ag in agents:
                    for other in agents:
                        if other is ag:
                            continue
                        booked = ag.bookie.get(other.actor_id)
                        if booked is None:
                            return False
                        with booked.read() as bv:
                            if not bv.contains_all((1, rows_per_agent)):
                                return False
                return True

            assert await wait_until(fully_booked, timeout=20.0)

            # healthy cluster: nobody marked anybody down
            for ag in agents:
                assert ag.membership.cluster_size == n_agents
        finally:
            for ag in agents:
                await shutdown(ag)

    asyncio.run(main())


def test_loadshed_drop_oldest_then_sync_repairs():
    """The reference's backpressure test shape (test_loadshed_handle_
    changes, handlers.rs:934-1018): shrink the ingestion queue so a
    broadcast flood forces drop-oldest, then prove the data plane heals
    — dropped changes are re-fetched by anti-entropy sync and the
    receiver still converges to the full row set."""
    from corrosion_tpu.runtime.metrics import METRICS

    async def main():
        net = MemNetwork(seed=29)
        a = await boot(net, "shed-a")
        # b: tiny processing queue + large flush threshold/timeout so the
        # buffer backs up between flushes and drop-oldest fires
        cfg = fast_config("shed-b", bootstrap=["shed-a"])
        cfg.perf.processing_queue_len = 2
        cfg.perf.apply_queue_len = 10_000
        cfg.perf.apply_queue_timeout_ms = 200
        b = await boot(net, "shed-b", cfg=cfg)
        try:
            assert await wait_until(
                lambda: all(ag.membership.cluster_size == 2 for ag in (a, b))
            )
            dropped0 = METRICS.counter("corro.agent.changes.dropped").value

            # flood: every insert is its own broadcast change version
            n_rows = 40
            for i in range(n_rows):
                await insert(a, i, f"flood-{i}")

            # the shrunken queue must actually shed under the flood
            assert await wait_until(
                lambda: METRICS.counter("corro.agent.changes.dropped").value
                > dropped0,
                timeout=10.0,
            ), "queue never shed — flood did not exceed processing_queue_len"

            # and anti-entropy repairs b to the full row set anyway
            assert await wait_until(
                lambda: count_rows(b) == n_rows, timeout=30.0
            ), count_rows(b)
            booked = b.bookie.get(a.actor_id)
            assert booked is not None
            with booked.read() as bv:
                assert bv.contains_all((1, n_rows))
        finally:
            await shutdown(a)
            await shutdown(b)

    asyncio.run(main())


def test_large_tx_multichunk_broadcast_replicates():
    """The reference's `large_tx_sync` shape (agent/tests.rs:602): one
    transaction large enough to split into multiple broadcast chunks
    must replicate whole. Regression for the r5 chaos-soak find: the
    ingest batch snapshot clobbered first-seen partials at commit, so
    chunk 2+ deduped as already-present and the version was silently
    lost with sync seeing nothing to repair."""

    async def main():
        net = MemNetwork(seed=31)
        a = await boot(net, "big-a")
        b = await boot(net, "big-b", bootstrap=("big-a",))
        assert await wait_until(
            lambda: a.membership.cluster_size >= 2
            and b.membership.cluster_size >= 2,
            timeout=15,
        )
        from corrosion_tpu.runtime import invariants

        # delta, not absolute: the registry is process-global and other
        # tests in the same run may have drained buffered versions
        drained_before = invariants.sometimes_registry().get(
            "buffered version drained", 0
        )
        big = "x" * 400
        await make_broadcastable_changes(
            a,
            lambda tx: [
                tx.execute(
                    "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                    (k, big),
                )
                for k in range(80)
            ],
        )
        assert await wait_progress(
            lambda: count_rows(b) == 80, lambda: count_rows(b)
        ), f"multi-chunk tx lost: b has {count_rows(b)}/80 rows"
        # the buffered partial actually drained (not a lucky one-chunk)
        assert (
            invariants.sometimes_registry().get("buffered version drained", 0)
            > drained_before
        )
        await shutdown(a)
        await shutdown(b)

    asyncio.run(main())

"""Tier-1 gate for the chaos matrix (scripts/traffic_sim.py).

Two layers:

1. BANKED-ARTIFACT GUARDS — TRAFFIC_SIM.json (the full 4-node,
   8-scenario matrix, heavy rungs banked-only) keeps its shape, its
   sha stamps, and the serving bars: zero op timeouts anywhere (the
   hang witness), availability floors, typed refusals counted where
   faults were injected, recovery + the closing zero-divergence
   verdict per scenario.
2. IN-SUITE TINY REPLICA — `run_matrix(tiny=True)` runs the 3-node
   {baseline, zombie-node, slow-disk, sick-disk} subset live: the same
   bars asserted against a real devcluster under real faults every
   tier-1 run — since r23 including the commit-stall page alert with
   its attached profile capture.

Margin discipline (r15 memory): the banked guards pin deterministic
facts only — counts, floors, verdicts — never wall-clock ratios; the
replica's wall is bounded by a wide backstop (the host drifts ±30%).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

PATH = os.path.join(REPO, "TRAFFIC_SIM.json")

FULL_SCENARIOS = (
    "baseline",
    "geo-latency",
    "asym-partition",
    "flap-storm",
    "churn-storm",
    "zombie-node",
    "slow-disk",
    "sick-disk",
)
STAGES = ("write", "query", "subscribe", "render")


@pytest.fixture(scope="module")
def banked() -> dict:
    with open(PATH) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def by_id(banked) -> dict:
    return {s["scenario"]: s for s in banked["scenarios"]}


def test_matrix_shape(banked, by_id):
    assert banked["mode"] == "full"
    assert banked["nodes"] == 4
    for sid in FULL_SCENARIOS:
        assert sid in by_id, f"missing scenario {sid}"
    for sid, rec in by_id.items():
        for stage in STAGES:
            assert stage in rec["stages"], f"{sid}: no {stage} stage"
        assert rec["injections"] or sid == "baseline"


def test_records_are_sha_stamped(banked):
    sha = banked.get("code_sha")
    assert sha and "corrosion_tpu/chaos/faults.py" in sha
    assert "corrosion_tpu/chaos/workload.py" in sha
    assert "corrosion_tpu/net/mem.py" in sha
    assert all(v != "missing" for v in sha.values()), sha
    assert banked.get("measured_at")


def test_no_op_ever_hit_its_deadline(by_id):
    """The matrix's standing bar: faults may shrink `ok`, they must
    never convert a request into a stall — zero timeouts across every
    stage of every scenario."""
    for sid, rec in by_id.items():
        for stage, st in rec["stages"].items():
            assert st["timeouts"] == 0, f"{sid}/{stage}"


def test_availability_floors(by_id):
    for sid, rec in by_id.items():
        for stage in ("write", "query"):
            st = rec["stages"][stage]
            assert st["attempts"] > 0, f"{sid}/{stage}: no traffic"
            floor = 0.98 if sid == "baseline" else 0.5
            assert st["availability"] >= floor, (
                f"{sid}/{stage}: {st['availability']}"
            )
            assert st["p50_secs"] is not None, f"{sid}/{stage}"
            assert st["p99_secs"] is not None, f"{sid}/{stage}"


def test_every_scenario_recovered_to_zero_divergence(by_id):
    """The closing verdict: after restore() every scenario's cluster
    converged (row counts equal everywhere, probe write delivered) and
    the divergence detector reported one view group."""
    for sid, rec in by_id.items():
        r = rec["recovery"]
        assert r["secs"] is not None, f"{sid}: never recovered"
        assert r["converged"], sid
        assert r["divergence_zero"], sid


def test_cluster_scorecard_was_scraped(by_id):
    """The percentiles come from the cluster's OWN planes: every
    scenario's /v1/slo scrape carries a populated write→event `total`
    stage, and /v1/cluster answered with full digest coverage."""
    for sid, rec in by_id.items():
        slo = rec.get("slo")
        assert slo and slo.get("total", {}).get("count"), (
            f"{sid}: /v1/slo total stage empty"
        )
        cl = rec.get("cluster")
        assert cl and cl.get("nodes_known"), f"{sid}: /v1/cluster empty"


def test_subscriptions_delivered_under_every_fault(by_id):
    for sid, rec in by_id.items():
        assert rec["events_delivered"] > 0, f"{sid}: no live events"


def test_churn_storm_banks_catchup_census(by_id):
    """r19 (closes the r18 open sub-item): the churn-storm record
    carries the RESTARTED node's /v1/status catch-up census — how it
    caught up (bootstrap state, held versions, resume waves, circuit
    state), not just that row counts converged."""
    cc = by_id["churn-storm"].get("catchup")
    assert cc, "churn-storm record has no catch-up census"
    for key in (
        "snapshot_enabled", "bootstrap", "held_versions",
        "resume_waves", "circuits_open",
    ):
        assert key in cc, f"catchup census missing {key}: {cc}"
    # the churned node rejoined holding real state
    assert cc["held_versions"] > 0


def test_alert_proof_banked_for_fault_scenarios(by_id):
    """r20: the drill-vs-outage proof — sick-disk's store-faults,
    slow-disk's commit-stall (r23) and zombie-node's view-divergence
    alerts each reached FIRING while the fault was injected (carrying
    the scenario as the drill mark, since the chaos census was live)
    and RESOLVED after restore()."""
    for sid, rule in (
        ("sick-disk", "store-faults"),
        ("slow-disk", "commit-stall"),
        ("zombie-node", "view-divergence"),
    ):
        al = by_id[sid].get("alerts")
        assert al, f"{sid}: no alert observation banked"
        assert al["expected"] == rule
        assert al["raised"], f"{sid}: {rule} never fired: {al['during']}"
        assert al["drill"] == sid, f"{sid}: drill mark {al['drill']!r}"
        assert al["resolved"], f"{sid}: {rule} stuck firing: {al['after']}"
        assert al["during"]["severity"] == "page"


def test_disk_incident_profiles_banked(by_id):
    """r23: the full-matrix bank carries the alert-triggered profile
    capture on each disk-pathology page alert, and the capture's
    dominant store-worker stack names the store commit path."""
    for sid in ("slow-disk", "sick-disk"):
        prof = (by_id[sid]["alerts"]["during"] or {}).get("profile")
        assert prof, f"{sid}: no profile attached to the firing alert"
        assert prof["reason"] == f"alert_{by_id[sid]['alerts']['expected']}"
        assert prof["samples"] > 0
        store_stacks = {
            k: v for k, v in prof["folded"].items()
            if k.startswith("store;")
        }
        assert store_stacks, f"{sid}: no store-worker stacks in capture"
        top = max(store_stacks, key=store_stacks.get)
        assert "store/crdt.py" in top, f"{sid}: {top}"


def test_injected_store_faults_surface_typed(by_id):
    """sick-disk: the injected SQLITE_BUSY/IO errors must appear as
    COUNTED typed refusals (the cluster answered; nothing hung)."""
    st = by_id["sick-disk"]["stages"]["write"]
    assert st["refusals"] > 0
    assert st["timeouts"] == 0


# -- the in-suite tiny replica ----------------------------------------------


def test_tier1_replica_serves_under_faults():
    """Live tiny-shape chaos: 3 nodes × {baseline, zombie-node,
    slow-disk, sick-disk} through the REAL HTTP/subscription surfaces.
    Every bar
    (`_assert_bars`) runs inside `run_matrix`; this test re-states the
    headline ones and bounds the wall with a wide backstop (nominal
    ~5 s — the ≤10 s replica budget — backstop for host drift plus the
    r21 load-tolerant alert-settle caps, which only spend their
    headroom when suite load starves the 0.08 s alert-eval cadence)."""
    import traffic_sim

    t0 = time.monotonic()
    record = asyncio.run(traffic_sim.run_matrix(tiny=True))
    elapsed = time.monotonic() - t0
    ids = [s["scenario"] for s in record["scenarios"]]
    # r22: the replica appends one remediation-ARMED zombie scenario
    # on a fresh tiny cluster — the supervisor boots, ticks, serves,
    # and every serving bar holds with the actuators live
    # r23: slow-disk joins the tiny subset — the commit-stall page
    # alert and its attached profile capture are tier-1 live bars
    assert ids == [
        "baseline", "zombie-node", "slow-disk", "sick-disk",
        "zombie-node-remediated",
    ]
    for rec in record["scenarios"]:
        for stage, st in rec["stages"].items():
            assert st["timeouts"] == 0, f"{rec['scenario']}/{stage}"
        assert rec["recovery"]["divergence_zero"], rec["scenario"]
    # tiny-shape sick disk fails every statement on the sick node:
    # typed refusals are deterministic, not a rate coin-flip
    sick = next(s for s in record["scenarios"] if s["scenario"] == "sick-disk")
    assert sick["stages"]["write"]["refusals"] > 0
    # r20: the injected store faults ALSO surfaced on the alerting
    # plane — the store-faults rule fired drill-marked while the sick
    # disk was live and resolved after restore (the same bar
    # _assert_bars holds inside run_matrix; re-stated here as the
    # replica's headline)
    al = sick["alerts"]
    assert al["expected"] == "store-faults"
    assert al["raised"] and al["resolved"]
    assert al["drill"] == "sick-disk"
    # r23, the replica's profiling headline (the same bar _assert_bars
    # holds live): the slow-disk commit-stall page alert fired with the
    # continuous profiler's capture attached, and the capture's
    # dominant store-worker stack names the store commit path — the
    # incident says WHERE the stalled wall went
    slow = next(
        s for s in record["scenarios"] if s["scenario"] == "slow-disk"
    )
    sal = slow["alerts"]
    assert sal["expected"] == "commit-stall"
    assert sal["raised"] and sal["resolved"]
    assert sal["drill"] == "slow-disk"
    prof = sal["during"]["profile"]
    assert prof and prof["reason"] == "alert_commit-stall"
    store_stacks = {
        k: v for k, v in prof["folded"].items() if k.startswith("store;")
    }
    assert store_stacks
    assert "store/crdt.py" in max(store_stacks, key=store_stacks.get)
    # r22: the standard replica runs OBSERVE-ONLY (the kill-switch
    # default) — the sick-disk store-faults firing must leave a typed
    # would_act audit trail, and no event may claim `acted`
    sick_rem = sick["remediation"]
    assert sick_rem["armed"] is False
    assert any(
        ev["mode"] == "would_act"
        and ev["action"] == "drain-refuse-bulk"
        and ev["rule"] == "store-faults"
        for ev in sick_rem["events"]
    ), sick_rem["events"]
    assert all(ev["mode"] != "acted" for ev in sick_rem["events"])
    # ...while the appended scenario ran with the plane ARMED
    armed = next(
        s for s in record["scenarios"]
        if s["scenario"] == "zombie-node-remediated"
    )
    assert armed["remediation"]["armed"] is True
    for ev in armed["remediation"]["events"]:
        assert ev["cooldown_secs"] > 0 and "wall" in ev, ev
    # budget: +~12 s over the old 28 s backstop for the armed addendum
    # (second cluster boot + the zombie alert poll spending its tiny
    # fire cap — the view-divergence gauge doesn't trip in a ~1 s
    # zombie window, a pre-existing tiny-shape limit), +~8 s for the
    # r23 slow-disk scenario (window + alert fire/resolve polls)
    assert elapsed < 48.0, f"tiny replica took {elapsed:.1f}s (budget 48s)"


# -- r22: the remediation A/B bank ------------------------------------------

ACTUATORS = {"targeted-sync", "drain-refuse-bulk", "shed-laggards"}
EVENT_MODES = {
    "acted", "would_act", "deferred", "refused", "failed", "reverted",
}


@pytest.fixture(scope="module")
def ab(banked) -> dict:
    rec = banked.get("remediation_ab")
    assert rec, "TRAFFIC_SIM.json has no remediation_ab bank (run " \
        "scripts/traffic_sim.py --remediation)"
    return rec


def test_remediation_ab_shape_and_stamps(ab):
    assert ab["tag"] == "r22"
    assert ab["sync_profile"]["sync_interval_min_secs"] >= 1.0, (
        "the A/B must run the production-shaped steady-sync profile — "
        "a hot sync cadence hides what remediation buys"
    )
    sha = ab["code_sha"]
    assert "corrosion_tpu/agent/remediation.py" in sha
    assert all(v != "missing" for v in sha.values()), sha
    assert ab.get("measured_at")
    for sid in FULL_SCENARIOS:
        assert sid in ab["scenarios"], f"A/B missing scenario {sid}"


def test_remediation_ab_zero_timeouts_and_availability_both_sides(ab):
    """Arming the plane must never convert a request into a stall or
    shrink availability below the matrix floors — on EITHER side."""
    for sid, row in ab["scenarios"].items():
        assert row["timeouts_off"] == 0, f"{sid}: off-side timeouts"
        assert row["timeouts_on"] == 0, f"{sid}: on-side timeouts"
        floor = 0.98 if sid == "baseline" else 0.5
        assert row["write_availability_off"] >= floor, sid
        assert row["write_availability_on"] >= floor, sid


def test_remediation_ab_recovery_strictly_improves(ab):
    """The headline: ≥3 FAULTED scenarios recover strictly faster with
    the actuators armed, and every claimed improvement is backed by the
    banked per-side walls."""
    improved = ab["improved_faulted"]
    assert len(improved) >= 3, improved
    assert "baseline" not in improved
    for sid in improved:
        row = ab["scenarios"][sid]
        assert row["improved"] is True
        assert row["recovery_on_secs"] < row["recovery_off_secs"], (
            f"{sid}: banked walls contradict the improved flag"
        )
    # both sides recovered EVERY scenario (the cap never tripped)
    for sid, row in ab["scenarios"].items():
        assert row["recovery_off_secs"] is not None, f"{sid}: off"
        assert row["recovery_on_secs"] is not None, f"{sid}: on"


def test_remediation_ab_every_action_typed_and_stamped(ab):
    """The audit bar: every event the armed run recorded is a typed
    actuator with its cooldown stamp and wall clock; at least one
    action actually fired, and the observe-only side left a would_act
    trail (the kill-switch proof)."""
    actions = ab["actions"]
    fired = [ev for ev in actions if ev["mode"] == "acted"]
    assert fired, "armed matrix fired no actions"
    for ev in actions:
        assert ev["action"] in ACTUATORS, ev
        assert ev["mode"] in EVENT_MODES, ev
        assert ev["cooldown_secs"] > 0, ev
        assert "wall" in ev and "rule" in ev, ev
    assert ab["observe_only_would_act"] > 0

"""Tier-1 gate for the chaos matrix (scripts/traffic_sim.py).

Two layers:

1. BANKED-ARTIFACT GUARDS — TRAFFIC_SIM.json (the full 4-node,
   8-scenario matrix, heavy rungs banked-only) keeps its shape, its
   sha stamps, and the serving bars: zero op timeouts anywhere (the
   hang witness), availability floors, typed refusals counted where
   faults were injected, recovery + the closing zero-divergence
   verdict per scenario.
2. IN-SUITE TINY REPLICA — `run_matrix(tiny=True)` runs the 3-node
   {baseline, zombie-node, sick-disk} subset live (~5 s nominal,
   budget ≤10 s): the same bars asserted against a real devcluster
   under real faults every tier-1 run.

Margin discipline (r15 memory): the banked guards pin deterministic
facts only — counts, floors, verdicts — never wall-clock ratios; the
replica's wall is bounded by a wide backstop (the host drifts ±30%).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

PATH = os.path.join(REPO, "TRAFFIC_SIM.json")

FULL_SCENARIOS = (
    "baseline",
    "geo-latency",
    "asym-partition",
    "flap-storm",
    "churn-storm",
    "zombie-node",
    "slow-disk",
    "sick-disk",
)
STAGES = ("write", "query", "subscribe", "render")


@pytest.fixture(scope="module")
def banked() -> dict:
    with open(PATH) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def by_id(banked) -> dict:
    return {s["scenario"]: s for s in banked["scenarios"]}


def test_matrix_shape(banked, by_id):
    assert banked["mode"] == "full"
    assert banked["nodes"] == 4
    for sid in FULL_SCENARIOS:
        assert sid in by_id, f"missing scenario {sid}"
    for sid, rec in by_id.items():
        for stage in STAGES:
            assert stage in rec["stages"], f"{sid}: no {stage} stage"
        assert rec["injections"] or sid == "baseline"


def test_records_are_sha_stamped(banked):
    sha = banked.get("code_sha")
    assert sha and "corrosion_tpu/chaos/faults.py" in sha
    assert "corrosion_tpu/chaos/workload.py" in sha
    assert "corrosion_tpu/net/mem.py" in sha
    assert all(v != "missing" for v in sha.values()), sha
    assert banked.get("measured_at")


def test_no_op_ever_hit_its_deadline(by_id):
    """The matrix's standing bar: faults may shrink `ok`, they must
    never convert a request into a stall — zero timeouts across every
    stage of every scenario."""
    for sid, rec in by_id.items():
        for stage, st in rec["stages"].items():
            assert st["timeouts"] == 0, f"{sid}/{stage}"


def test_availability_floors(by_id):
    for sid, rec in by_id.items():
        for stage in ("write", "query"):
            st = rec["stages"][stage]
            assert st["attempts"] > 0, f"{sid}/{stage}: no traffic"
            floor = 0.98 if sid == "baseline" else 0.5
            assert st["availability"] >= floor, (
                f"{sid}/{stage}: {st['availability']}"
            )
            assert st["p50_secs"] is not None, f"{sid}/{stage}"
            assert st["p99_secs"] is not None, f"{sid}/{stage}"


def test_every_scenario_recovered_to_zero_divergence(by_id):
    """The closing verdict: after restore() every scenario's cluster
    converged (row counts equal everywhere, probe write delivered) and
    the divergence detector reported one view group."""
    for sid, rec in by_id.items():
        r = rec["recovery"]
        assert r["secs"] is not None, f"{sid}: never recovered"
        assert r["converged"], sid
        assert r["divergence_zero"], sid


def test_cluster_scorecard_was_scraped(by_id):
    """The percentiles come from the cluster's OWN planes: every
    scenario's /v1/slo scrape carries a populated write→event `total`
    stage, and /v1/cluster answered with full digest coverage."""
    for sid, rec in by_id.items():
        slo = rec.get("slo")
        assert slo and slo.get("total", {}).get("count"), (
            f"{sid}: /v1/slo total stage empty"
        )
        cl = rec.get("cluster")
        assert cl and cl.get("nodes_known"), f"{sid}: /v1/cluster empty"


def test_subscriptions_delivered_under_every_fault(by_id):
    for sid, rec in by_id.items():
        assert rec["events_delivered"] > 0, f"{sid}: no live events"


def test_churn_storm_banks_catchup_census(by_id):
    """r19 (closes the r18 open sub-item): the churn-storm record
    carries the RESTARTED node's /v1/status catch-up census — how it
    caught up (bootstrap state, held versions, resume waves, circuit
    state), not just that row counts converged."""
    cc = by_id["churn-storm"].get("catchup")
    assert cc, "churn-storm record has no catch-up census"
    for key in (
        "snapshot_enabled", "bootstrap", "held_versions",
        "resume_waves", "circuits_open",
    ):
        assert key in cc, f"catchup census missing {key}: {cc}"
    # the churned node rejoined holding real state
    assert cc["held_versions"] > 0


def test_alert_proof_banked_for_fault_scenarios(by_id):
    """r20: the drill-vs-outage proof — sick-disk's store-faults and
    zombie-node's view-divergence alerts each reached FIRING while the
    fault was injected (carrying the scenario as the drill mark, since
    the chaos census was live) and RESOLVED after restore()."""
    for sid, rule in (
        ("sick-disk", "store-faults"),
        ("zombie-node", "view-divergence"),
    ):
        al = by_id[sid].get("alerts")
        assert al, f"{sid}: no alert observation banked"
        assert al["expected"] == rule
        assert al["raised"], f"{sid}: {rule} never fired: {al['during']}"
        assert al["drill"] == sid, f"{sid}: drill mark {al['drill']!r}"
        assert al["resolved"], f"{sid}: {rule} stuck firing: {al['after']}"
        assert al["during"]["severity"] == "page"


def test_injected_store_faults_surface_typed(by_id):
    """sick-disk: the injected SQLITE_BUSY/IO errors must appear as
    COUNTED typed refusals (the cluster answered; nothing hung)."""
    st = by_id["sick-disk"]["stages"]["write"]
    assert st["refusals"] > 0
    assert st["timeouts"] == 0


# -- the in-suite tiny replica ----------------------------------------------


def test_tier1_replica_serves_under_faults():
    """Live tiny-shape chaos: 3 nodes × {baseline, zombie-node,
    sick-disk} through the REAL HTTP/subscription surfaces.  Every bar
    (`_assert_bars`) runs inside `run_matrix`; this test re-states the
    headline ones and bounds the wall with a wide backstop (nominal
    ~5 s — the ≤10 s replica budget — backstop for host drift plus the
    r21 load-tolerant alert-settle caps, which only spend their
    headroom when suite load starves the 0.08 s alert-eval cadence)."""
    import traffic_sim

    t0 = time.monotonic()
    record = asyncio.run(traffic_sim.run_matrix(tiny=True))
    elapsed = time.monotonic() - t0
    ids = [s["scenario"] for s in record["scenarios"]]
    assert ids == ["baseline", "zombie-node", "sick-disk"]
    for rec in record["scenarios"]:
        for stage, st in rec["stages"].items():
            assert st["timeouts"] == 0, f"{rec['scenario']}/{stage}"
        assert rec["recovery"]["divergence_zero"], rec["scenario"]
    # tiny-shape sick disk fails every statement on the sick node:
    # typed refusals are deterministic, not a rate coin-flip
    sick = next(s for s in record["scenarios"] if s["scenario"] == "sick-disk")
    assert sick["stages"]["write"]["refusals"] > 0
    # r20: the injected store faults ALSO surfaced on the alerting
    # plane — the store-faults rule fired drill-marked while the sick
    # disk was live and resolved after restore (the same bar
    # _assert_bars holds inside run_matrix; re-stated here as the
    # replica's headline)
    al = sick["alerts"]
    assert al["expected"] == "store-faults"
    assert al["raised"] and al["resolved"]
    assert al["drill"] == "sick-disk"
    assert elapsed < 28.0, f"tiny replica took {elapsed:.1f}s (budget 10s)"

"""OTLP/HTTP span export against a fake collector.

Reference behavior: `klukai/src/main.rs:68-118` — OTLP exporter + batch
span processor behind `config.telemetry.open-telemetry`, resource attrs
service.name / service.version / host.name.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from corrosion_tpu.runtime import otel, trace
from corrosion_tpu.runtime.metrics import METRICS


class _Collector(BaseHTTPRequestHandler):
    bodies: list  # set per-server

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length))
        self.server.bodies.append((self.path, body))
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def collector():
    srv = HTTPServer(("127.0.0.1", 0), _Collector)
    srv.bodies = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()
    otel.configure(None)


def _all_spans(srv):
    spans = []
    for _path, body in srv.bodies:
        for rs in body["resourceSpans"]:
            for ss in rs["scopeSpans"]:
                spans.extend(ss["spans"])
    return spans


def test_span_export_parent_linkage_and_resource(collector):
    port = collector.server_address[1]
    otel.configure(
        f"http://127.0.0.1:{port}",
        resource_attrs={"corrosion.actor_id": "deadbeef"},
        flush_interval_s=60.0,  # flush manually; no timing dependence
    )
    with trace.span("sync.serve", peer="a1") as parent:
        with trace.span("sync.send_chunk") as child:
            pass
    otel.exporter().flush()

    path, body = collector.bodies[0]
    assert path == "/v1/traces"
    res_attrs = {
        a["key"]: a["value"] for a in body["resourceSpans"][0]["resource"]["attributes"]
    }
    assert res_attrs["service.name"]["stringValue"] == "corrosion-tpu"
    assert res_attrs["corrosion.actor_id"]["stringValue"] == "deadbeef"
    assert "host.name" in res_attrs

    spans = _all_spans(collector)
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"sync.serve", "sync.send_chunk"}
    p, c = by_name["sync.serve"], by_name["sync.send_chunk"]
    # same trace, child points at parent (hex ids per OTLP/JSON mapping)
    assert p["traceId"] == c["traceId"] == parent.ctx.trace_id
    assert c["parentSpanId"] == p["spanId"] == parent.ctx.span_id
    assert c["spanId"] == child.ctx.span_id
    assert "parentSpanId" not in p
    # nanosecond decimal-string timestamps, start <= end
    assert int(p["startTimeUnixNano"]) <= int(p["endTimeUnixNano"])
    # child attrs carried
    attrs = {a["key"]: a["value"] for a in p["attributes"]}
    assert attrs["peer"]["stringValue"] == "a1"


def test_error_status_and_continue_from(collector):
    port = collector.server_address[1]
    otel.configure(f"http://127.0.0.1:{port}", flush_interval_s=60.0)
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with pytest.raises(RuntimeError):
        with trace.continue_from(tp, "ingest.apply"):
            raise RuntimeError("boom")
    otel.exporter().flush()
    (s,) = _all_spans(collector)
    assert s["traceId"] == "ab" * 16  # adopted the wire trace id
    assert s["parentSpanId"] == "cd" * 8
    assert s["status"] == {"code": 2}


def test_unsampled_spans_not_exported(collector):
    port = collector.server_address[1]
    otel.configure(f"http://127.0.0.1:{port}", flush_interval_s=60.0)
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-00"  # flags 00: unsampled
    with trace.continue_from(tp, "quiet"):
        pass
    otel.exporter().flush()
    assert _all_spans(collector) == []


def test_queue_drop_oldest_accounting(collector):
    port = collector.server_address[1]
    exp = otel.configure(
        f"http://127.0.0.1:{port}", queue_max=4, flush_interval_s=60.0
    )
    dropped0 = METRICS.counter("corro_otel_spans_dropped_total").value
    for i in range(7):
        exp.record({"name": f"s{i}", "traceId": "00", "spanId": "00"})
    exp.flush()
    spans = _all_spans(collector)
    assert [s["name"] for s in spans] == ["s3", "s4", "s5", "s6"]
    assert METRICS.counter("corro_otel_spans_dropped_total").value - dropped0 == 3


def test_unconfigured_is_noop():
    otel.configure(None)
    with trace.span("free"):
        pass  # must not raise, must not export
    assert otel.exporter() is None


def test_export_failure_counted():
    # unreachable collector: failures counted, no exception escapes
    exp = otel.configure(
        "http://127.0.0.1:1", flush_interval_s=60.0, timeout_s=0.5
    )
    fail0 = METRICS.counter("corro_otel_export_failures_total").value
    with trace.span("doomed"):
        pass
    exp.flush()
    assert METRICS.counter("corro_otel_export_failures_total").value == fail0 + 1
    otel.configure(None)

"""Pins for the r15 direct change capture (store/capture.py).

1. Randomized equivalence: CORRO_CAPTURE=direct must emit byte/clock-
   identical changes AND leave byte-identical data/rows/clock tables vs
   CORRO_CAPTURE=trigger (the pre-r15 AFTER-trigger path, kept intact)
   across mixed INSERT / OR REPLACE / OR IGNORE / upsert / UPDATE /
   DELETE / executemany / dict-param transactions — with raw SQL
   (expressions, pk changes, non-pk WHERE) interleaved mid-transaction
   so the in-memory and trigger-drained streams must merge in exact
   statement order.
2. Zero `__crdt_pending` statements on a fully-captured transaction
   (the tentpole's bypass, pinned via the sqlite trace callback), while
   CORRO_CAPTURE=trigger still runs the pending round-trip.
3. The fused encode: every locally-committed Change carries wire_cell
   bytes identical to a fresh `write_change` encode, and the changeset
   body built from cached cells is byte-identical to an uncached one.
4. Direct-captured grouped writes still replicate to a gossiping peer.
"""

from __future__ import annotations

import dataclasses
import random

import pytest

from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp

from tests.test_finalize_batch import SCHEMA, SITE, dump_state


def mk_store() -> CrdtStore:
    st = CrdtStore(":memory:", site_id=SITE)
    st.apply_schema_sql(SCHEMA)
    return st


def random_txs(rng: random.Random, n_txs: int) -> list:
    """Transactions as [(mode, sql, params)] with mode x=execute,
    m=executemany; mixes captured shapes with raw-SQL fallbacks."""
    txs = []
    for _ in range(n_txs):
        ops = []
        for _ in range(rng.randint(1, 6)):
            kind = rng.random()
            kv = rng.randint(1, 6)
            if kind < 0.16:
                ops.append((
                    "x",
                    "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
                    (kv, rng.choice(["x", "y", ""]), rng.randint(0, 9)),
                ))
            elif kind < 0.26:
                # named params through the SAME captured path
                ops.append((
                    "x",
                    "INSERT INTO kv (id, a, b) VALUES (:id, :a, :b)",
                    {"id": kv, "a": "n", "b": rng.randint(0, 3)},
                ))
            elif kind < 0.34:
                ops.append((
                    "x",
                    "INSERT OR IGNORE INTO kv (id, a) VALUES (?, ?)",
                    (kv, "ig"),
                ))
            elif kind < 0.44:
                ops.append((
                    "x",
                    "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)"
                    " ON CONFLICT (id) DO UPDATE SET"
                    " a = excluded.a, b = ?",
                    (kv, "up", rng.randint(0, 5), rng.randint(6, 9)),
                ))
            elif kind < 0.54:
                ops.append((
                    "x",
                    "UPDATE kv SET a = ?, b = ? WHERE id = ?",
                    (rng.choice(["p", "q"]), rng.randint(0, 9), kv),
                ))
            elif kind < 0.60:
                # expression in SET: raw SQL → trigger capture, merged
                # mid-stream with the direct captures around it
                ops.append((
                    "x",
                    "UPDATE kv SET a = ?, b = b + 1 WHERE id = ?",
                    ("expr", kv),
                ))
            elif kind < 0.68:
                ops.append(("x", "DELETE FROM kv WHERE id = ?", (kv,)))
            elif kind < 0.74:
                # pk change = delete+create, trigger path
                ops.append((
                    "x",
                    "UPDATE kv SET id = ? WHERE id = ?",
                    (rng.randint(7, 9), kv),
                ))
            elif kind < 0.84:
                ops.append((
                    "m",
                    "INSERT OR REPLACE INTO pair (k, g, v) VALUES (?, ?, ?)",
                    [
                        (
                            rng.choice(["a", "b"]),
                            rng.randint(1, 3),
                            rng.choice([None, "w", "z"]),
                        )
                        for _ in range(3)
                    ],
                ))
            elif kind < 0.92:
                ops.append((
                    "x",
                    "DELETE FROM pair WHERE k = ? AND g = ?",
                    (rng.choice(["a", "b"]), rng.randint(1, 3)),
                ))
            else:
                # NULL rowid-alias pk: captured via lastrowid
                ops.append((
                    "x",
                    "INSERT INTO kv (id, a) VALUES (NULL, ?)",
                    ("auto",),
                ))
        txs.append(ops)
    return txs


def run_engine(monkeypatch, engine: str, txs) -> tuple:
    monkeypatch.setenv("CORRO_CAPTURE", engine)
    st = mk_store()
    all_changes = []
    for i, ops in enumerate(txs):
        with st.write_tx(Timestamp.from_unix(i + 1)) as tx:
            for mode, sql, params in ops:
                try:
                    if mode == "m":
                        tx.executemany(sql, params)
                    else:
                        tx.execute(sql, params)
                except Exception:
                    pass  # e.g. pk-change collision: both engines skip alike
            changes, _v, _ls = tx.commit()
        all_changes.append([
            (c.table, c.pk, c.cid, c.val, c.col_version, c.db_version,
             c.seq, c.cl)
            for c in changes
        ])
    dump = dump_state(st)
    st.close()
    return all_changes, dump


@pytest.mark.parametrize("seed", [2, 11, 29, 83])
def test_direct_capture_equivalent_to_trigger(monkeypatch, seed):
    rng = random.Random(seed)
    txs = random_txs(rng, 30)
    ch_trig, dump_trig = run_engine(monkeypatch, "trigger", txs)
    ch_dir, dump_dir = run_engine(monkeypatch, "direct", txs)
    assert ch_dir == ch_trig
    assert dump_dir == dump_trig


def test_merged_stream_ordering_explicit(monkeypatch):
    """One tx interleaving captured → raw → captured statements: seq
    assignment proves the trigger-drained rows splice at the exact
    statement position."""
    txs = [
        [("x", "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (1, "x", 1)),
         ("x", "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (2, "y", 2))],
        [
            ("x", "UPDATE kv SET a = ? WHERE id = ?", ("d1", 1)),  # direct
            ("x", "UPDATE kv SET a = a || '!' , b = b + 1 WHERE id = ?",
             (2,)),  # raw: expression
            ("x", "DELETE FROM kv WHERE id = ?", (1,)),  # direct
            ("x", "INSERT INTO kv (id, a, b) VALUES (3, 'z', 3)", ()),
        ],
    ]
    ch_trig, dump_trig = run_engine(monkeypatch, "trigger", txs)
    ch_dir, dump_dir = run_engine(monkeypatch, "direct", txs)
    assert ch_dir == ch_trig
    assert dump_dir == dump_trig


def test_delete_reinsert_same_tx_equivalence(monkeypatch):
    txs = [
        [("x", "INSERT INTO kv (id, a, b) VALUES (1, 'x', 1)", ())],
        [
            ("x", "DELETE FROM kv WHERE id = 1", ()),
            ("x", "INSERT INTO kv (id, a, b) VALUES (1, 'y', 2)", ()),
            ("x", "UPDATE kv SET a = 'z' WHERE id = 1", ()),
        ],
        [("x", "DELETE FROM kv WHERE id = 1", ())],
        [("x", "INSERT INTO kv (id, a) VALUES (1, 'back')", ())],
    ]
    ch_trig, dump_trig = run_engine(monkeypatch, "trigger", txs)
    ch_dir, dump_dir = run_engine(monkeypatch, "direct", txs)
    assert ch_dir == ch_trig
    assert dump_dir == dump_trig


def test_affinity_and_pending_munging_equivalence(monkeypatch):
    """Values that sqlite converts on storage (float→int on INTEGER
    affinity, int→text on TEXT affinity) and that the pending table's
    NUMERIC affinity munges must capture identically; numeric-looking
    text falls back to the trigger path rather than guessing."""
    txs = [
        [("x", "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (1.0, 7, 2.0)),
         ("x", "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
          (2, "55", 3)),  # numeric-looking text → fallback, still equal
         ("x", "UPDATE kv SET b = ? WHERE id = ?", (4.0, 1.0))],
        [("x", "INSERT OR REPLACE INTO kv (id, a, b) VALUES (2, 'lit', 9)",
          ())],
    ]
    ch_trig, dump_trig = run_engine(monkeypatch, "trigger", txs)
    ch_dir, dump_dir = run_engine(monkeypatch, "direct", txs)
    assert ch_dir == ch_trig
    assert dump_dir == dump_trig


# -- the bypass itself ------------------------------------------------------


def _trace_tx(monkeypatch, engine: str) -> tuple:
    monkeypatch.setenv("CORRO_CAPTURE", engine)
    st = mk_store()
    with st.write_tx(Timestamp.from_unix(1)) as tx:
        tx.executemany(
            "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
            [(i, f"v{i}", i) for i in range(10)],
        )
        tx.commit()
    stmts: list = []
    st._conn.set_trace_callback(stmts.append)
    with st.write_tx(Timestamp.from_unix(2)) as tx:
        tx.executemany(
            "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
            [(i, f"w{i}", i + 1) for i in range(10)],
        )
        tx.execute("UPDATE kv SET a = ? WHERE id = ?", ("z", 3))
        tx.execute("DELETE FROM kv WHERE id = ?", (9,))
        changes, version, _ls = tx.commit()
    st._conn.set_trace_callback(None)
    st.close()
    return stmts, changes, version


def test_fully_captured_tx_never_touches_pending(monkeypatch):
    """The tentpole pin: a transaction of recognized statements runs
    ZERO `__crdt_pending` statements — no trigger INSERTs, no readback
    SELECT, no DELETE."""
    stmts, changes, version = _trace_tx(monkeypatch, "direct")
    pending = [s for s in stmts if "__crdt_pending" in s]
    assert pending == [], pending
    assert version > 0 and changes


def test_trigger_engine_restores_pending_round_trip(monkeypatch):
    """CORRO_CAPTURE=trigger keeps the pre-r15 capture path: the same
    transaction logs through __crdt_pending and reads it back."""
    stmts, changes_t, _v = _trace_tx(monkeypatch, "trigger")
    # trigger-body INSERTs run inside sqlite (not surfaced by the trace
    # callback); the drain round-trip is the observable signature
    kinds = {s.split()[0].upper() for s in stmts if "__crdt_pending" in s}
    assert {"SELECT", "DELETE"} <= kinds, stmts
    # and the two engines emitted identical changes for identical input
    _s, changes_d, _v2 = _trace_tx(monkeypatch, "direct")
    assert [dataclasses.replace(c, wire_cell=None) for c in changes_d] == [
        dataclasses.replace(c, wire_cell=None) for c in changes_t
    ]


def test_capture_metrics_accounting(monkeypatch):
    from corrosion_tpu.runtime.metrics import METRICS

    monkeypatch.setenv("CORRO_CAPTURE", "direct")
    direct0 = METRICS.counter("corro.write.capture.direct.total").value
    trig0 = METRICS.counter("corro.write.capture.trigger.total").value
    st = mk_store()
    with st.write_tx(Timestamp.from_unix(1)) as tx:
        tx.execute(
            "INSERT INTO kv (id, a, b) VALUES (?, ?, ?)", (1, "x", 1)
        )  # direct
        tx.execute(
            "UPDATE kv SET b = b + 1 WHERE id = ?", (1,)
        )  # raw → trigger
        tx.commit()
    st.close()
    assert METRICS.counter("corro.write.capture.direct.total").value == (
        direct0 + 1
    )
    assert METRICS.counter("corro.write.capture.trigger.total").value == (
        trig0 + 1
    )


# -- fused encode -----------------------------------------------------------


def test_wire_cell_matches_fresh_encode(monkeypatch):
    from corrosion_tpu.types.change import ChangeV1, ChangesetFull
    from corrosion_tpu.types.codec import (
        Writer,
        encode_change_v1_body,
        write_change,
    )

    monkeypatch.setenv("CORRO_CAPTURE", "direct")
    st = mk_store()
    with st.write_tx(Timestamp.from_unix(1)) as tx:
        tx.executemany(
            "INSERT OR REPLACE INTO kv (id, a, b) VALUES (?, ?, ?)",
            [(i, f"v{i}", i) for i in range(5)],
        )
        tx.execute("DELETE FROM kv WHERE id = ?", (0,))
        changes, version, last_seq = tx.commit()
    st.close()
    assert changes
    for c in changes:
        assert c.wire_cell is not None
        w = Writer()
        write_change(w, dataclasses.replace(c, wire_cell=None))
        assert w.bytes() == c.wire_cell
    cached = ChangeV1(
        actor_id=SITE,
        changeset=ChangesetFull(
            version, tuple(changes), (0, last_seq), last_seq,
            Timestamp.from_unix(1),
        ),
    )
    bare = ChangeV1(
        actor_id=SITE,
        changeset=ChangesetFull(
            version,
            tuple(dataclasses.replace(c, wire_cell=None) for c in changes),
            (0, last_seq), last_seq, Timestamp.from_unix(1),
        ),
    )
    assert encode_change_v1_body(cached) == encode_change_v1_body(bare)


# -- live replication -------------------------------------------------------


def test_direct_captured_writes_replicate_to_peer():
    """Direct-captured grouped writes broadcast and converge on a
    gossiping peer (the end-to-end safety net for the capture bypass)."""
    import asyncio

    from tests.test_agent import boot, wait_until

    from corrosion_tpu.agent.run import (
        make_broadcastable_changes,
        shutdown,
    )
    from corrosion_tpu.net.mem import MemNetwork

    def _ins(i: int):
        rows = [(i * 10 + j, f"cap{i}-{j}") for j in range(3)]
        return lambda tx: [tx.executemany(
            "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)", rows
        )]

    async def main():
        net = MemNetwork(seed=67)
        a = await boot(net, "agent-cap-a")
        b = await boot(net, "agent-cap-b", bootstrap=["agent-cap-a"])
        assert a.store.direct_capture and b.store.direct_capture
        try:
            await wait_until(lambda: len(a.members) >= 1, timeout=10)
            await asyncio.gather(
                *(make_broadcastable_changes(a, _ins(i)) for i in range(6))
            )

            def applied():
                row = b.store._conn.execute(
                    "SELECT count(*) AS n FROM tests"
                ).fetchone()
                return row["n"] == 18

            assert await wait_until(applied, timeout=20)
        finally:
            await shutdown(b)
            await shutdown(a)

    asyncio.run(main())

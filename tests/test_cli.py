"""Black-box CLI tests: real subprocess agent + CLI client commands.

Mirrors `integration-tests/tests/cli_test.rs` (help/query stdout against a
live agent) plus backup/restore/tls/db-lock coverage."""

import asyncio
import os
import signal
import socket
import sqlite3
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


from tests.test_agent import free_port  # noqa: E402  (shared port helper)


def cli_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    # the CLI never needs jax; keep subprocess start fast
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def run_cli(args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "corrosion_tpu", *args],
        capture_output=True,
        text=True,
        env=cli_env(),
        timeout=60,
        **kw,
    )


def test_help():
    out = subprocess.run(
        [sys.executable, "-m", "corrosion_tpu", "--help"],
        capture_output=True,
        text=True,
        env=cli_env(),
        timeout=60,
    )
    assert out.returncode == 0
    for word in ("agent", "backup", "restore", "cluster", "query", "exec",
                 "template", "tls", "subs", "locks"):
        assert word in out.stdout


def write_config(tmp_path, api_port, gossip_port) -> str:
    db = tmp_path / "corrosion.db"
    schema = tmp_path / "schema.sql"
    schema.write_text(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
    )
    admin = tmp_path / "admin.sock"
    cfg = tmp_path / "corrosion.toml"
    cfg.write_text(
        f"""
[db]
path = "{db}"
schema_paths = ["{schema}"]

[api]
bind_addr = ["127.0.0.1:{api_port}"]

[gossip]
bind_addr = "127.0.0.1:{gossip_port}"

[admin]
uds_path = "{admin}"
"""
    )
    return str(cfg)


@pytest.fixture(scope="module")
def live_agent(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("cli")
    api_port, gossip_port = free_port(), free_port()
    cfg = write_config(tmp_path, api_port, gossip_port)
    proc = subprocess.Popen(
        [sys.executable, "-m", "corrosion_tpu", "-c", cfg, "agent"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=cli_env(),
    )
    # wait for the api to come up
    deadline = time.monotonic() + 30
    up = False
    while time.monotonic() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", api_port), 0.2)
            s.close()
            up = True
            break
        except OSError:
            if proc.poll() is not None:
                break
            time.sleep(0.1)
    if not up:
        out = proc.stdout.read() if proc.poll() is not None else ""
        proc.kill()
        raise RuntimeError(f"agent did not come up: {out}")
    yield {"cfg": cfg, "tmp": tmp_path, "api_port": api_port}
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(15)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_exec_and_query(live_agent):
    cfg = live_agent["cfg"]
    r = run_cli(
        ["-c", cfg, "exec",
         "INSERT INTO tests (id, text) VALUES (1, 'hello')"]
    )
    assert r.returncode == 0, r.stderr
    assert '"rows_affected": 1' in r.stdout

    r = run_cli(["-c", cfg, "query", "SELECT text FROM tests", "--columns"])
    assert r.returncode == 0, r.stderr
    assert r.stdout.splitlines() == ["text", "hello"]

    # --timeout threads through to the server-side statement interrupt
    # (main.rs:672 Query.timeout); an overrunning query exits 1 with the
    # interrupt error instead of running to completion
    slow = (
        "WITH RECURSIVE c(x) AS "
        "(SELECT 1 UNION ALL SELECT x+1 FROM c WHERE x < 300000000) "
        "SELECT count(*) FROM c"
    )
    r = run_cli(["-c", cfg, "query", slow, "--timeout", "0.3"])
    assert r.returncode == 1
    assert "interrupt" in r.stderr.lower()

    # exec --timeout: the interrupted write surfaces as a clean error
    # line (HTTP 400 -> exit 1), never a traceback
    r = run_cli(
        ["-c", cfg, "exec", f"INSERT INTO tests (id, text) {slow.replace('SELECT count(*)', 'SELECT 99, count(*)')}",
         "--timeout", "0.3"]
    )
    assert r.returncode == 1
    assert "interrupt" in r.stderr.lower()
    assert "Traceback" not in r.stderr


def test_admin_over_cli(live_agent):
    cfg = live_agent["cfg"]
    r = run_cli(["-c", cfg, "cluster", "membership-states"])
    assert r.returncode == 0, r.stderr
    assert '"self": true' in r.stdout

    r = run_cli(["-c", cfg, "sync", "generate"])
    assert r.returncode == 0, r.stderr
    assert '"heads"' in r.stdout

    r = run_cli(["-c", cfg, "locks"])
    assert r.returncode == 0, r.stderr

    r = run_cli(["-c", cfg, "subs", "list"])
    assert r.returncode == 0, r.stderr


def test_alerts_over_cli(live_agent):
    """r20: `corrosion alerts` renders the live agent's rule-state
    table (GET /v1/alerts), raw JSON with --json, and the any-node
    cluster rollup with --cluster."""
    cfg = live_agent["cfg"]
    r = run_cli(["-c", cfg, "alerts"])
    assert r.returncode == 0, r.stderr
    assert "health score" in r.stdout
    for rule in ("slo-burn", "loop-lag", "view-divergence",
                 "store-faults"):
        assert rule in r.stdout, r.stdout

    r = run_cli(["-c", cfg, "alerts", "--json"])
    assert r.returncode == 0, r.stderr
    import json as _json

    body = _json.loads(r.stdout)
    assert body["enabled"] and len(body["rules"]) >= 7

    r = run_cli(["-c", cfg, "alerts", "--cluster"])
    assert r.returncode == 0, r.stderr
    assert "cluster alerts" in r.stdout


def test_profile_over_cli(live_agent):
    """r23: `corrosion profile` round-trips the live agent's continuous
    profiler (GET /v1/profile) — summary table, raw JSON, a valid
    speedscope file on disk, folded text, and the cluster rollup."""
    cfg = live_agent["cfg"]
    tmp = live_agent["tmp"]
    import json as _json

    # the always-on sampler needs a beat to accumulate samples
    deadline = time.monotonic() + 20
    body = {}
    while time.monotonic() < deadline:
        r = run_cli(["-c", cfg, "profile", "--json"])
        assert r.returncode == 0, r.stderr
        body = _json.loads(r.stdout)
        assert body.get("enabled"), body
        if body.get("samples", 0) > 0:
            break
        time.sleep(0.5)
    assert body.get("samples", 0) > 0, body
    assert body["top_self"], body

    r = run_cli(["-c", cfg, "profile"])
    assert r.returncode == 0, r.stderr
    assert "samples over" in r.stdout and "frame" in r.stdout

    # speedscope export round-trip: the file on disk is the document
    out = tmp / "prof.speedscope.json"
    r = run_cli(["-c", cfg, "profile", "--speedscope", str(out)])
    assert r.returncode == 0, r.stderr
    doc = _json.loads(out.read_text())
    assert doc["$schema"].endswith("file-format-schema.json")
    assert doc["profiles"][0]["type"] == "sampled"
    assert len(doc["shared"]["frames"]) > 0

    r = run_cli(["-c", cfg, "profile", "--folded"])
    assert r.returncode == 0, r.stderr
    # every folded line is "stack count" with a subsystem;task prefix
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert lines
    for ln in lines:
        stack, n = ln.rsplit(" ", 1)
        assert int(n) > 0
        assert stack.count(";") >= 1, ln

    r = run_cli(["-c", cfg, "profile", "--cluster"])
    assert r.returncode == 0, r.stderr
    assert "cluster hotspots" in r.stdout


def test_snapshot_dump_then_install_roundtrip(tmp_path):
    """r17 catch-up plane parity with the backup/restore block:
    `snapshot dump` builds the compressed container, `snapshot install`
    swaps it in schema-sha-gated while preserving the target's own
    site id — the offline halves of the peer-protocol bootstrap."""
    api_port, gossip_port = free_port(), free_port()
    cfg = write_config(tmp_path, api_port, gossip_port)
    db = tmp_path / "corrosion.db"
    sys.path.insert(0, str(REPO))
    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.base import Timestamp

    store = CrdtStore(str(db))
    store.apply_schema_sql(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
    )
    for i in range(3):
        with store.write_tx(Timestamp(i + 1)) as tx:
            tx.execute(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"s{i}")
            )
    store.close()

    snap_file = tmp_path / "out" / "cold.snapshot"
    snap_file.parent.mkdir()
    r = run_cli(["-c", cfg, "snapshot", "dump", str(snap_file)])
    assert r.returncode == 0, r.stderr
    assert "watermark versions" in r.stdout and snap_file.exists()

    # install over a SECOND node's db: rows land, identity is kept
    cold_dir = tmp_path / "cold"
    cold_dir.mkdir()
    cold_cfg = write_config(cold_dir, free_port(), free_port())
    cold_store = CrdtStore(str(cold_dir / "corrosion.db"))
    cold_store.apply_schema_sql(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
    )
    cold_site = cold_store.site_id
    cold_store.close()
    r = run_cli(["-c", cold_cfg, "snapshot", "install", str(snap_file)])
    assert r.returncode == 0, r.stderr
    conn = sqlite3.connect(cold_dir / "corrosion.db")
    assert conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0] == 3
    assert (
        bytes(conn.execute("SELECT site_id FROM __crdt_site").fetchone()[0])
        == cold_site.bytes16
    )
    conn.close()

    # schema-sha gate: a node configured with a different schema refuses
    other_dir = tmp_path / "other"
    other_dir.mkdir()
    other_cfg = write_config(other_dir, free_port(), free_port())
    (other_dir / "schema.sql").write_text(
        "CREATE TABLE different (id INTEGER NOT NULL PRIMARY KEY);"
    )
    r = run_cli(["-c", other_cfg, "snapshot", "install", str(snap_file)])
    assert r.returncode == 1
    assert "schema" in r.stderr.lower()


def test_backup_then_restore_roundtrip(tmp_path):
    api_port, gossip_port = free_port(), free_port()
    cfg = write_config(tmp_path, api_port, gossip_port)
    db = tmp_path / "corrosion.db"
    # seed without an agent: direct store writes
    sys.path.insert(0, str(REPO))
    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.base import Timestamp

    store = CrdtStore(str(db))
    store.apply_schema_sql(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT);"
    )
    with store.write_tx(Timestamp(1)) as tx:
        tx.execute("INSERT INTO tests (id, text) VALUES (1, 'seed')")
        tx.commit()
    store.close()

    bak = tmp_path / "out" / "backup.db"
    r = run_cli(["-c", cfg, "backup", str(bak)])
    assert r.returncode == 0, r.stderr
    assert bak.exists()
    # per-node state scrubbed from the copy
    conn = sqlite3.connect(bak)
    assert conn.execute("SELECT COUNT(*) FROM __corro_members").fetchone()[0] == 0
    assert conn.execute("SELECT text FROM tests").fetchone()[0] == "seed"
    conn.close()

    # damage the live db (through the store: CRR triggers need its
    # registered SQL functions), then restore the backup over it
    store = CrdtStore(str(db))
    with store.write_tx(Timestamp(2)) as tx:
        tx.execute("UPDATE tests SET text = 'damaged'")
        tx.commit()
    store.close()
    r = run_cli(["-c", cfg, "restore", str(bak)])
    assert r.returncode == 0, r.stderr
    conn = sqlite3.connect(db)
    assert conn.execute("SELECT text FROM tests").fetchone()[0] == "seed"
    conn.close()


def test_tls_generate(tmp_path):
    # the CLI subcommand imports corrosion_tpu.tls in the subprocess,
    # which needs the optional `cryptography` package
    pytest.importorskip(
        "cryptography",
        reason="`tls generate` needs the optional `cryptography` package",
    )
    ca_cert = tmp_path / "ca-cert.pem"
    ca_key = tmp_path / "ca-key.pem"
    r = run_cli(
        ["tls", "ca", "generate",
         "--cert-file", str(ca_cert), "--key-file", str(ca_key)]
    )
    assert r.returncode == 0, r.stderr
    assert ca_cert.exists() and ca_key.exists()
    assert b"BEGIN CERTIFICATE" in ca_cert.read_bytes()

    sc = tmp_path / "server-cert.pem"
    sk = tmp_path / "server-key.pem"
    r = run_cli(
        ["tls", "server", "generate", "127.0.0.1",
         "--ca-cert", str(ca_cert), "--ca-key", str(ca_key),
         "--cert-file", str(sc), "--key-file", str(sk)]
    )
    assert r.returncode == 0, r.stderr
    assert sc.exists() and sk.exists()

    cc = tmp_path / "client-cert.pem"
    ck = tmp_path / "client-key.pem"
    r = run_cli(
        ["tls", "client", "generate",
         "--ca-cert", str(ca_cert), "--ca-key", str(ca_key),
         "--cert-file", str(cc), "--key-file", str(ck)]
    )
    assert r.returncode == 0, r.stderr
    # server cert verifies against the CA
    from cryptography import x509

    ca = x509.load_pem_x509_certificate(ca_cert.read_bytes())
    srv = x509.load_pem_x509_certificate(sc.read_bytes())
    assert srv.issuer == ca.subject
    srv.verify_directly_issued_by(ca)


def test_db_lock_runs_command_under_lock(tmp_path):
    api_port, gossip_port = free_port(), free_port()
    cfg = write_config(tmp_path, api_port, gossip_port)
    db = tmp_path / "corrosion.db"
    sqlite3.connect(db).close()
    r = run_cli(["-c", cfg, "db", "lock", "echo locked-ok"])
    assert r.returncode == 0, r.stderr
    assert "locked-ok" in r.stdout


def test_corrosion_client_local_read_pool(tmp_path):
    """CorrosionClient (klukai-client lib.rs:365-403): API client + direct
    read-only sqlite pool over the local db file."""
    import asyncio

    from corrosion_tpu.client import CorrosionClient
    from corrosion_tpu.store.crdt import CrdtStore
    from corrosion_tpu.types.base import Timestamp

    db = str(tmp_path / "local.db")
    store = CrdtStore(db)
    store.apply_schema_sql("CREATE TABLE lt (id INTEGER PRIMARY KEY, v TEXT);")
    with store.write_tx(Timestamp.now()) as tx:
        tx.execute("INSERT INTO lt (id, v) VALUES (1, 'direct')")
    store.close()

    async def main():
        client = CorrosionClient("127.0.0.1:1", db)  # API addr unused here
        rows = client.local_query("SELECT id, v FROM lt")
        assert rows == [(1, "direct")]
        # read-only: writes through the pool must fail
        import sqlite3 as s3

        import pytest as pt

        with client.read() as conn, pt.raises(s3.OperationalError):
            conn.execute("INSERT INTO lt (id, v) VALUES (2, 'nope')")
        # pool reuse: same connection object comes back
        with client.read() as c1:
            first = id(c1)
        with client.read() as c2:
            assert id(c2) == first
        await client.close()

    asyncio.run(main())

"""Plaintext QUIC lane: wire-format vectors, handshake, the three gossip
lanes, loss recovery, and integrity-tag rejection.

Counterpart of the reference's `quinn_plaintext.rs` test (basic_test:
client opens a uni stream to a plaintext server) plus the transport-lane
behavior of `transport.rs:81-140`.  Interop caveat: no Rust toolchain in
the image, so both ends are this repo's stack over real UDP sockets; the
byte-layout tests pin the RFC 9000 wire format and the SeaHash vectors
pin the tag primitive (the two halves a quinn peer would check).
"""

from __future__ import annotations

import asyncio
import struct

import pytest

from corrosion_tpu.net import seahash
from corrosion_tpu.net.quic import (
    CID_LEN,
    F_ACK,
    MIN_INITIAL,
    PnRanges,
    QUIC_V1,
    QuicEndpoint,
    QuicTransport,
    Reassembler,
    TAG_LEN,
    TP_ISCID,
    decode_pn,
    decode_transport_params,
    encode_transport_params,
    parse_ack_frame,
    read_vint,
    vint,
)


# -- seahash: the crate's published vectors ---------------------------------


def test_seahash_crate_vectors():
    assert seahash.hash_bytes(b"to be or not to be") == 1988685042348123509
    assert (
        seahash.hash_bytes(b"love is a wonderful terrible thing")
        == 4784284276849692846
    )


def test_seahash_streaming_equals_buffered():
    data = bytes(range(256)) * 5  # 1280 bytes, crosses many 32B blocks
    whole = seahash.hash_bytes(data)
    h = seahash.SeaHasher()
    # feed in awkward unaligned pieces
    for cut in (1, 3, 7, 8, 13, 100, 31):
        h.write(data[:cut])
        data = data[cut:]
    h.write(data)
    assert h.finish() == whole


def test_plaintext_tag_shape():
    t = seahash.tag(b"hdr", b"payload")
    assert len(t) == TAG_LEN
    assert t != seahash.tag(b"hdr", b"payloae")
    assert t != seahash.tag(b"hdR", b"payload")


# -- varints: RFC 9000 §A.1 examples ----------------------------------------


def test_varint_rfc_vectors():
    cases = [
        (bytes.fromhex("c2197c5eff14e88c"), 151288809941952652),
        (bytes.fromhex("9d7f3e7d"), 494878333),
        (bytes.fromhex("7bbd"), 15293),
        (bytes.fromhex("25"), 37),
    ]
    for raw, val in cases:
        got, pos = read_vint(raw, 0)
        assert (got, pos) == (val, len(raw))
    # encode picks the minimal length
    assert vint(37) == b"\x25"
    assert vint(15293) == bytes.fromhex("7bbd")
    assert vint(494878333) == bytes.fromhex("9d7f3e7d")
    assert vint(151288809941952652) == bytes.fromhex("c2197c5eff14e88c")


def test_pn_decode_rfc_example():
    # RFC 9000 §A.3: largest received 0xa82f30ea, truncated 0x9b32 in 2
    # bytes decodes to 0xa82f9b32
    assert decode_pn(0x9B32, 2, 0xA82F30EA + 1) == 0xA82F9B32


# -- transport params / ack ranges ------------------------------------------


def test_transport_params_roundtrip():
    params = {TP_ISCID: b"\x01" * 8, 0x04: 1 << 20, 0x01: 30000}
    enc = encode_transport_params(params)
    dec = decode_transport_params(enc)
    assert dec[TP_ISCID] == b"\x01" * 8
    assert read_vint(dec[0x04], 0)[0] == 1 << 20


def test_ack_ranges_roundtrip():
    r = PnRanges()
    for pn in [0, 1, 2, 5, 6, 9, 3]:
        assert r.add(pn)
    assert not r.add(5)  # duplicate detected
    assert r.ranges == [[0, 3], [5, 6], [9, 9]]
    frame = r.ack_frame()
    ftype, pos = read_vint(frame, 0)
    assert ftype == F_ACK
    ranges, end = parse_ack_frame(frame, pos, ecn=False)
    assert end == len(frame)
    assert sorted(ranges) == [(0, 3), (5, 6), (9, 9)]


def test_reassembler_out_of_order_and_overlap():
    asm = Reassembler()
    assert asm.feed(4, b"efgh") == b""
    assert asm.feed(0, b"abcd") == b"abcdefgh"
    assert asm.feed(2, b"cdef") == b""  # stale overlap ignored
    assert asm.feed(8, b"ij", fin=True) == b"ij"
    assert asm.finished


# -- packet layout golden ----------------------------------------------------


def test_client_initial_packet_layout():
    """First client datagram: RFC 9000 long-header Initial, ≥1200 bytes,
    CRYPTO frame carrying exactly the transport parameters (the
    plaintext session's whole handshake, quinn_plaintext.rs:196-220),
    sealed with the SeaHash tag."""

    async def main():
        ep = await QuicEndpoint.bind("127.0.0.1", 0)
        sent = []
        ep._sendto = lambda data, peer: sent.append(data)
        try:
            await asyncio.wait_for(ep.connect("127.0.0.1:1"), 0.4)
        except Exception:
            pass  # no server: connect times out after retransmits
        await ep.close()
        return sent

    sent = asyncio.new_event_loop().run_until_complete(main())
    assert sent, "client sent no Initial"
    pkt = sent[0]
    assert len(pkt) >= MIN_INITIAL
    first = pkt[0]
    assert first & 0x80, "long header form bit"
    assert first & 0x40, "fixed bit"
    assert (first >> 4) & 0x03 == 0, "Initial packet type"
    pn_len = (first & 0x03) + 1
    assert struct.unpack(">I", pkt[1:5])[0] == QUIC_V1
    dcl = pkt[5]
    pos = 6 + dcl
    scl = pkt[pos]
    scid = pkt[pos + 1 : pos + 1 + scl]
    assert scl == CID_LEN
    pos += 1 + scl
    token_len, pos = read_vint(pkt, pos)
    assert token_len == 0
    length, pos = read_vint(pkt, pos)
    header_end = pos + pn_len
    header = pkt[:header_end]
    body = pkt[header_end : pos + length]
    payload, tag = body[:-TAG_LEN], body[-TAG_LEN:]
    assert seahash.tag(header, payload) == tag
    # first frame: CRYPTO(off=0) with the transport params
    ftype, fpos = read_vint(payload, 0)
    assert ftype == 0x06
    off, fpos = read_vint(payload, fpos)
    ln, fpos = read_vint(payload, fpos)
    assert off == 0
    tps = decode_transport_params(payload[fpos : fpos + ln])
    assert tps[TP_ISCID] == bytes(scid)
    # the remainder of the packet is PADDING (zero bytes)
    assert set(payload[fpos + ln :]) <= {0}


# -- end-to-end lanes --------------------------------------------------------


def _lane_fixture():
    """(server_endpoint, sinks) with all three lane handlers wired."""
    sinks = {"dgram": [], "uni": [], "bi": []}

    async def on_dgram(src, data):
        sinks["dgram"].append(data)

    async def on_uni(src, frame):
        sinks["uni"].append(frame)

    async def on_bi(stream):
        while True:
            f = await stream.recv()
            if f is None:
                break
            sinks["bi"].append(f)
            await stream.send(b"echo:" + f)
        await stream.finish()

    return sinks, on_dgram, on_uni, on_bi


def test_three_lanes_end_to_end():
    async def main():
        sinks, on_dgram, on_uni, on_bi = _lane_fixture()
        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(on_dgram, on_uni, on_bi)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(client)

        await t.send_datagram(server.addr, b"swim-probe")
        for i in range(5):
            await t.send_uni(server.addr, b"bcast-%d" % i)
        bi = await t.open_bi(server.addr)
        await bi.send(b"sync-start")
        await bi.send(b"sync-need")
        await bi.finish()
        assert await asyncio.wait_for(bi.recv(), 5) == b"echo:sync-start"
        assert await asyncio.wait_for(bi.recv(), 5) == b"echo:sync-need"
        assert await asyncio.wait_for(bi.recv(), 5) is None
        await asyncio.sleep(0.2)
        assert sinks["dgram"] == [b"swim-probe"]
        assert sorted(sinks["uni"]) == [b"bcast-%d" % i for i in range(5)]
        assert sinks["bi"] == [b"sync-start", b"sync-need"]
        # the server observed exactly one connection for all lanes
        assert len(server.conns_by_scid) == 1
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_handshake_survives_packet_loss():
    """Drop 30% of datagrams (deterministic pattern): PTO retransmission
    must still complete the handshake and deliver all lane traffic."""

    async def main():
        sinks, on_dgram, on_uni, on_bi = _lane_fixture()
        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(on_dgram, on_uni, on_bi)
        client = await QuicEndpoint.bind("127.0.0.1", 0)

        drop_counter = [0]
        for ep in (server, client):
            real = ep._sendto

            def lossy(data, peer, _real=real):
                drop_counter[0] += 1
                if drop_counter[0] % 3 == 0:
                    return  # dropped
                _real(data, peer)

            ep._sendto = lossy

        t = QuicTransport(client)
        await t.send_uni(server.addr, b"lossy-broadcast")
        bi = await t.open_bi(server.addr)
        await bi.send(b"lossy-sync")
        await bi.finish()
        assert await asyncio.wait_for(bi.recv(), 20) == b"echo:lossy-sync"
        for _ in range(100):
            if sinks["uni"]:
                break
            await asyncio.sleep(0.1)
        assert sinks["uni"] == [b"lossy-broadcast"]
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 60))


def test_corrupted_tag_rejected_connection_survives():
    async def main():
        sinks, on_dgram, on_uni, on_bi = _lane_fixture()
        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(on_dgram, on_uni, on_bi)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(client)
        await t.send_datagram(server.addr, b"first")
        await asyncio.sleep(0.1)
        # inject a short-header packet with a flipped tag at the server:
        # it must be dropped (quinn_plaintext decrypt CryptoError) and
        # the connection must keep working
        conn = t._conns[server.addr]
        server_conn = next(iter(server.conns_by_scid.values()))
        fake = bytes([0x43]) + server_conn.scid + struct.pack(">I", 999)
        payload = b"\x01"  # PING
        bad = fake + payload + b"\x00" * TAG_LEN
        server._on_udp(bad, conn.endpoint._udp_transport.get_extra_info("sockname")[:2])
        await t.send_datagram(server.addr, b"second")
        await asyncio.sleep(0.2)
        assert sinks["dgram"] == [b"first", b"second"]
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_large_bi_transfer_flow_control():
    """1 MiB each way over one bi stream: exercises chunking, ack-clocked
    draining, and MAX_DATA / MAX_STREAM_DATA replenishment."""

    async def main():
        blob = bytes(range(256)) * 4096  # 1 MiB
        received = []

        async def on_bi(stream):
            while True:
                f = await stream.recv()
                if f is None:
                    break
                received.append(f)
            await stream.send(blob)
            await stream.finish()

        server = await QuicEndpoint.bind("127.0.0.1", 0)

        async def nope(*a):
            pass

        server.serve(nope, nope, on_bi)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(client)
        bi = await t.open_bi(server.addr)
        await bi.send(blob)
        await bi.finish()
        back = await asyncio.wait_for(bi.recv(), 60)
        assert back == blob
        assert received == [blob]
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 90))


def test_uni_stream_limit_replenished():
    """600 one-shot uni broadcasts cross the initial 256-stream limit:
    MAX_STREAMS replenishment must keep the lane flowing
    (api/peer/mod.rs:121-150's 256 uni stream budget)."""

    async def main():
        got = []

        async def on_uni(src, frame):
            got.append(frame)

        async def nope(*a):
            pass

        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(nope, on_uni, nope)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(client)
        for i in range(600):
            await t.send_uni(server.addr, b"b%04d" % i)
        for _ in range(200):
            if len(got) >= 600:
                break
            await asyncio.sleep(0.05)
        assert len(got) == 600
        assert sorted(got) == [b"b%04d" % i for i in range(600)]
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 60))


def test_idle_timeout_reaps_connection():
    async def main():
        server = await QuicEndpoint.bind("127.0.0.1", 0)

        async def nope(*a):
            pass

        server.serve(nope, nope, nope)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(client, idle_timeout=0.5)
        await t.send_datagram(server.addr, b"x")
        conn = t._conns[server.addr]
        await asyncio.wait_for(conn.closed.wait(), 10)
        # next send transparently reconnects
        await t.send_datagram(server.addr, b"y")
        assert t._conns[server.addr] is not conn
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_two_agents_replicate_over_quic():
    """Full-stack: two real agents on loopback plaintext-QUIC transports
    gossip membership (SWIM datagrams), replicate a row (uni broadcast),
    and a late joiner syncs (bi streams) — the reference's three quinn
    lanes (`transport.rs:81-140`) end-to-end through this stack."""
    from tests.test_agent import (
        TEST_SCHEMA,
        FAST_SWIM,
        count_rows,
        fast_config,
        free_port,
        insert,
        wait_until,
    )
    from corrosion_tpu.agent.run import run, setup, shutdown

    async def main():
        agents = []
        addrs = [f"127.0.0.1:{free_port(dgram=True)}" for _ in range(2)]
        for addr in addrs:
            cfg = fast_config(addr, bootstrap=[a for a in addrs if a != addr])
            cfg.gossip.transport = "quic"
            agent = await setup(cfg, network=None)
            agent.membership.config = FAST_SWIM
            agent.store.apply_schema_sql(TEST_SCHEMA)
            await run(agent)
            agents.append(agent)

        a, b = agents
        assert await wait_until(
            lambda: len(a.members.states) >= 1 and len(b.members.states) >= 1
        ), "QUIC agents never saw each other"
        await insert(a, 1, "quic-row")
        assert await wait_until(lambda: count_rows(b) == 1), (
            "row did not replicate over QUIC broadcast"
        )
        # late joiner: must catch up via bi-stream sync
        late_addr = f"127.0.0.1:{free_port(dgram=True)}"
        cfg = fast_config(late_addr, bootstrap=list(addrs))
        cfg.gossip.transport = "quic"
        c = await setup(cfg, network=None)
        c.membership.config = FAST_SWIM
        c.store.apply_schema_sql(TEST_SCHEMA)
        await run(c)
        agents.append(c)
        assert await wait_until(lambda: count_rows(c) == 1, timeout=20), (
            "late joiner did not sync over QUIC bi streams"
        )
        for agent in agents:
            await shutdown(agent)

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 120))


def test_client_socket_spread():
    """Outbound-endpoint spread parity (transport.rs:57-71, 170-173):
    dials leave through dial-only client sockets picked by SeaHash of
    the peer addr mod the socket count, the serving socket never
    originates dials, and transport.close() reaps the dial sockets."""

    async def main():
        sinks, on_dgram, on_uni, on_bi = _lane_fixture()
        servers = []
        for _ in range(6):
            s = await QuicEndpoint.bind("127.0.0.1", 0)
            s.serve(on_dgram, on_uni, on_bi)
            servers.append(s)
        identity = await QuicEndpoint.bind("127.0.0.1", 0)
        clients = [await QuicEndpoint.bind("127.0.0.1", 0) for _ in range(3)]
        t = QuicTransport(identity, client_endpoints=clients)
        for s in servers:
            await t.send_datagram(s.addr, b"probe")
        await asyncio.sleep(0.3)
        assert len(sinks["dgram"]) == 6
        # each dial left through exactly the socket the reference's
        # formula picks, deterministically per peer
        from corrosion_tpu.net import seahash

        for s in servers:
            idx = seahash.hash_bytes(s.addr.encode()) % len(clients)
            assert t._conns[s.addr].endpoint is clients[idx]
        # the serving identity socket originated no outbound connections
        assert not identity.conns_by_scid
        await t.close()
        for ep in clients:
            assert ep._udp_transport.is_closing()
        for s in servers:
            await s.close()
        await identity.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_dial_only_socket_refuses_inbound():
    """A spread socket (accept_inbound=False, quinn client-endpoint
    shape) must not spawn a server-role connection for a stray Initial
    on its unauthenticated open port."""
    from corrosion_tpu.net.quic import QuicError

    async def main():
        dial_only = await QuicEndpoint.bind(
            "127.0.0.1", 0, accept_inbound=False
        )
        other = await QuicEndpoint.bind("127.0.0.1", 0)
        t = QuicTransport(other)
        with pytest.raises(QuicError, match="timeout"):
            await t.send_datagram(dial_only.addr, b"stray")
        assert not dial_only.conns_by_scid
        assert not dial_only.conns_by_peer
        await t.close()
        await other.close()
        await dial_only.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_rtt_observed_on_dialer_side_only():
    """RTT samples feed the members rings keyed by the addr the dialer
    dialed (transport.rs rtt_tx, client connect path); the accept side
    must NOT observe RTT — its peer_addr is the dialer's ephemeral
    spread socket, which would grow members.rtts/per-addr metrics
    without bound and never match a member identity."""

    async def main():
        sinks, on_dgram, on_uni, on_bi = _lane_fixture()
        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(on_dgram, on_uni, on_bi)
        server_t = QuicTransport(server)
        server_seen = []
        server_t.observe_rtt = lambda addr, rtt: server_seen.append(addr)

        identity = await QuicEndpoint.bind("127.0.0.1", 0)
        spread = await QuicEndpoint.bind(
            "127.0.0.1", 0, accept_inbound=False
        )
        t = QuicTransport(identity, client_endpoints=[spread])
        client_seen = []
        t.observe_rtt = lambda addr, rtt: client_seen.append(addr)

        await t.send_datagram(server.addr, b"ping")
        await asyncio.sleep(0.5)  # let handshake/app ACKs generate samples
        assert sinks["dgram"] == [b"ping"]
        # dialer keys samples by the advertised addr it dialed
        assert client_seen and set(client_seen) == {server.addr}
        # accept side never keys by the ephemeral source
        assert server_seen == []
        await t.close()
        await server_t.close()
        await identity.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


def test_agent_spread_socket_count():
    """config.rs:162-163 / transport.rs:57-71: the agent builds 8 dial
    sockets for the default client_addr (port 0) and exactly 1 when an
    operator pins a client port."""
    from tests.test_agent import fast_config
    from corrosion_tpu.agent.run import setup, shutdown

    async def main():
        cfg = fast_config("127.0.0.1:0", bootstrap=[])
        cfg.gossip.transport = "quic"
        agent = await setup(cfg, network=None)
        try:
            assert len(agent.transport._client_eps) == 8
            assert all(
                not ep.accept_inbound
                for ep in agent.transport._client_eps
            )
        finally:
            await shutdown(agent)

        cfg2 = fast_config("127.0.0.1:0", bootstrap=[])
        cfg2.gossip.transport = "quic"
        cfg2.gossip.client_addr = "127.0.0.1:0"  # port 0 -> still spread
        agent2 = await setup(cfg2, network=None)
        try:
            assert len(agent2.transport._client_eps) == 8
        finally:
            await shutdown(agent2)

        import socket

        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        pinned = s.getsockname()[1]
        s.close()
        cfg3 = fast_config("127.0.0.1:0", bootstrap=[])
        cfg3.gossip.transport = "quic"
        cfg3.gossip.client_addr = f"127.0.0.1:{pinned}"
        agent3 = await setup(cfg3, network=None)
        try:
            eps = agent3.transport._client_eps
            assert len(eps) == 1
            assert eps[0].addr == f"127.0.0.1:{pinned}"
        finally:
            await shutdown(agent3)

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 60))


def test_quic_requires_plaintext_mode():
    from corrosion_tpu.agent.run import setup
    from corrosion_tpu.runtime.config import Config
    from corrosion_tpu.runtime.tmpdb import fresh_db_path

    async def main():
        cfg = Config()
        cfg.db.path = fresh_db_path()
        cfg.gossip.bind_addr = "127.0.0.1:0"
        cfg.gossip.transport = "quic"
        cfg.gossip.plaintext = False
        with pytest.raises(ValueError, match="plaintext"):
            await setup(cfg)

    asyncio.new_event_loop().run_until_complete(main())


def test_mtu_knob_caps_datagrams():
    """gossip.max_mtu parity (api/peer/mod.rs:121-150): an endpoint
    bound with a smaller MTU advertises it and never emits a larger
    UDP payload."""

    async def main():
        got = []

        async def on_dgram(src, data):
            got.append(data)

        async def nope(*a):
            pass

        server = await QuicEndpoint.bind("127.0.0.1", 0, mtu=1300)
        server.serve(on_dgram, nope, nope)
        client = await QuicEndpoint.bind("127.0.0.1", 0, mtu=1300)
        sizes = []
        real = client._sendto

        def spy(data, peer):
            sizes.append(len(data))
            real(data, peer)

        client._sendto = spy
        t = QuicTransport(client)
        await t.send_datagram(server.addr, b"x" * 1100)
        await asyncio.sleep(0.2)
        assert got == [b"x" * 1100]
        assert max(sizes) <= 1300
        conn = t._conns[server.addr]
        import pytest as _pytest

        with _pytest.raises(Exception, match="too large"):
            await conn.send_datagram(b"y" * 1290)
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 30))


# -- GSO: sendmsg/UDP_SEGMENT coalescing ------------------------------------


def test_gso_grouping_rules():
    """gso_groups: equal-size runs coalesce, a shorter trailer rides the
    batch, a larger datagram starts a new one, kernel caps are honored."""
    from corrosion_tpu.net.quic import GSO_MAX_SEGS, gso_groups

    a, b = b"a" * 1200, b"b" * 1200
    t = b"t" * 700
    assert gso_groups([a, b, t]) == [(1200, [a, b, t])]
    # one full segment + shorter trailer is a valid 2-segment batch
    assert gso_groups([a, t]) == [(1200, [a, t])]
    # a LARGER datagram cannot trail: it starts a new group
    big = b"c" * 1300
    assert [len(g) for _, g in gso_groups([a, b, big])] == [2, 1]
    # segment-count cap (kernel UDP_MAX_SEGMENTS; 500 B segments so the
    # byte cap stays out of the way)
    e = b"e" * 500
    many = [e] * (GSO_MAX_SEGS + 3)
    assert [len(g) for _, g in gso_groups(many)] == [GSO_MAX_SEGS, 3]
    # byte cap binds first for MTU-size segments: 65000 // 1200 = 54
    assert [len(g) for _, g in gso_groups([a] * 60)] == [54, 6]
    # total-byte cap: two 33 KB datagrams exceed one IP datagram
    j = bytes(33000)
    assert [len(g) for _, g in gso_groups([j, j])] == [1, 1]
    # order is preserved across group boundaries
    flat = [g for _, grp in gso_groups([a, big, t]) for g in grp]
    assert flat == [a, big, t]


def test_gso_engages_on_bulk_transfer():
    """A bulk stream flush coalesces equal-size datagrams into UDP_SEGMENT
    sendmsg batches; the kernel re-segments so the peer sees normal QUIC
    datagrams.  Where the kernel refuses GSO the endpoint falls back and
    the transfer must still be byte-identical (asserted either way)."""
    from corrosion_tpu.runtime.metrics import METRICS

    async def main():
        blob = bytes(range(256)) * 1024  # 256 KiB
        received = []

        async def on_bi(stream):
            while True:
                f = await stream.recv()
                if f is None:
                    break
                received.append(f)
            await stream.send(b"ok")
            await stream.finish()

        async def nope(*a):
            pass

        server = await QuicEndpoint.bind("127.0.0.1", 0)
        server.serve(nope, nope, on_bi)
        client = await QuicEndpoint.bind("127.0.0.1", 0)
        seg_before = METRICS.counter("corro.quic.gso.segments").value
        bat_before = METRICS.counter("corro.quic.gso.batches").value
        div_before = METRICS.counter("corro.quic.gso.diverted").value
        t = QuicTransport(client)
        bi = await t.open_bi(server.addr)
        await bi.send(blob)
        await bi.finish()
        ack = await asyncio.wait_for(bi.recv(), 60)
        assert ack == b"ok"
        assert b"".join(received) == blob
        segments = METRICS.counter("corro.quic.gso.segments").value - seg_before
        batches = METRICS.counter("corro.quic.gso.batches").value - bat_before
        diverted = METRICS.counter("corro.quic.gso.diverted").value - div_before
        if client._gso_ok:
            # on a GSO-capable kernel every bulk flush either coalesced
            # or was explicitly diverted (write buffer / would-block);
            # silent non-engagement is a regression
            assert batches > 0 or diverted > 0
        if batches and not diverted:
            # coalescing health, asserted only on an unloaded run: with
            # zero diversions the 10-datagram flush budget should yield
            # well above the 2-segment floor.  Under load, diverted
            # flushes can leave only small tail batches — not a failure.
            assert segments / batches >= 3
        await t.close()
        await client.close()
        await server.close()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(main(), 90))

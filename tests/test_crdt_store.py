"""CrdtStore: schema, local write capture, merge semantics, convergence.

Semantics under test mirror cr-sqlite's observable behavior as consumed by
the reference (column LWW with value tie-break + merge-equal-values,
causal-length deletes, sentinel rows, db_version/seq assignment); the
convergence tests replay the same operations in different orders on
independent stores and require identical final states — the core CRDT
property the whole system rests on.
"""

import itertools

import pytest

from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.store.schema import SchemaError, parse_sql
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import SENTINEL

SCHEMA = """
CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE tests2 (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
CREATE TABLE testsblob (id BLOB NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');
"""
# ^ same shape as the reference's TEST_SCHEMA (klukai-tests/src/lib.rs:13)


def mk_store(site_byte=1):
    s = CrdtStore(":memory:", site_id=ActorId(bytes([site_byte]) * 16))
    s.apply_schema_sql(SCHEMA)
    return s


def write(store, sql, params=(), ts=None):
    with store.write_tx(ts or Timestamp.now()) as tx:
        tx.execute(sql, params)
        return tx.commit()


def rows(store, table="tests"):
    return [tuple(r) for r in store._conn.execute(f"SELECT * FROM {table} ORDER BY 1")]


# -- schema engine ---------------------------------------------------------


def test_schema_constraints():
    with pytest.raises(SchemaError, match="primary key"):
        parse_sql("CREATE TABLE nopk (a INTEGER);")
    with pytest.raises(SchemaError, match="UNIQUE"):
        parse_sql("CREATE TABLE u (id INTEGER PRIMARY KEY, x TEXT UNIQUE);")
    with pytest.raises(SchemaError, match="foreign keys"):
        parse_sql(
            "CREATE TABLE a (id INTEGER PRIMARY KEY);"
            "CREATE TABLE b (id INTEGER PRIMARY KEY,"
            " a_id INTEGER REFERENCES a(id));"
        )
    with pytest.raises(SchemaError, match="DEFAULT"):
        parse_sql("CREATE TABLE n (id INTEGER PRIMARY KEY, x TEXT NOT NULL);")
    ok = parse_sql(SCHEMA)
    assert set(ok.tables) == {"tests", "tests2", "testsblob"}
    assert ok.tables["tests"].pk_cols == ["id"]
    assert ok.tables["tests"].non_pk_cols == ["text"]


def test_schema_add_column_and_index():
    store = mk_store()
    store.apply_schema_sql(
        SCHEMA + "\nCREATE INDEX tests_text ON tests (text);"
    )
    assert "tests_text" in store.schema.tables["tests"].indexes
    # add a column
    new = SCHEMA.replace(
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');",
        "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '', num INTEGER);",
    )
    store.apply_schema_sql(new)
    write(store, "INSERT INTO tests (id, text, num) VALUES (1, 'a', 5)")
    assert rows(store) == [(1, "a", 5)]


def test_schema_destructive_refused():
    store = mk_store()
    with pytest.raises(SchemaError, match="destructive"):
        store.apply_schema_sql(
            "CREATE TABLE tests (id INTEGER NOT NULL PRIMARY KEY, text TEXT NOT NULL DEFAULT '');"
        )  # drops tests2/testsblob


# -- local write capture ---------------------------------------------------


def test_insert_produces_changes():
    store = mk_store()
    changes, db_version, last_seq = write(
        store, "INSERT INTO tests (id, text) VALUES (1, 'hello')"
    )
    assert db_version == 1
    cids = [c.cid for c in changes]
    assert cids == [SENTINEL, "text"]
    assert [c.seq for c in changes] == [0, 1]
    assert last_seq == 1
    assert changes[0].cl == 1 and changes[1].cl == 1
    assert changes[1].val == "hello"
    assert changes[1].col_version == 1
    assert all(c.site_id == store.site_id.bytes16 for c in changes)


def test_update_bumps_col_version():
    store = mk_store()
    write(store, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    changes, db_version, _ = write(store, "UPDATE tests SET text = 'b' WHERE id = 1")
    assert db_version == 2
    assert len(changes) == 1
    assert changes[0].cid == "text"
    assert changes[0].col_version == 2
    assert changes[0].cl == 1


def test_noop_update_produces_nothing():
    store = mk_store()
    write(store, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    changes, db_version, _ = write(store, "UPDATE tests SET text = 'a' WHERE id = 1")
    assert changes == [] and db_version == 0


def test_delete_produces_even_cl_sentinel():
    store = mk_store()
    write(store, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    changes, _, _ = write(store, "DELETE FROM tests WHERE id = 1")
    assert len(changes) == 1
    assert changes[0].cid == SENTINEL
    assert changes[0].cl == 2
    assert changes[0].is_delete()
    assert rows(store) == []


def test_reinsert_after_delete_bumps_cl():
    store = mk_store()
    write(store, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    write(store, "DELETE FROM tests WHERE id = 1")
    changes, _, _ = write(store, "INSERT INTO tests (id, text) VALUES (1, 'b')")
    sentinel = [c for c in changes if c.cid == SENTINEL][0]
    assert sentinel.cl == 3  # resurrection: odd again
    col = [c for c in changes if c.cid == "text"][0]
    assert col.cl == 3 and col.col_version == 1  # fresh causal epoch


def test_pk_change_is_delete_plus_create():
    # UPDATE that changes the pk must replicate as delete(old)+create(new)
    a, b = mk_store(1), mk_store(2)
    ch1, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'x')")
    replicate(ch1, b)
    ch2, _, _ = write(a, "UPDATE tests SET id = 2 WHERE id = 1")
    assert ch2, "pk change must produce changes"
    replicate(ch2, b)
    assert rows(a) == rows(b) == [(2, "x")]


def test_pk_change_with_value_update():
    a, b = mk_store(1), mk_store(2)
    ch1, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'x')")
    replicate(ch1, b)
    ch2, _, _ = write(a, "UPDATE tests SET id = 3, text = 'y' WHERE id = 1")
    replicate(ch2, b)
    assert rows(a) == rows(b) == [(3, "y")]


def test_read_conn_close_is_safe():
    store = mk_store()
    write(store, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    rc = store.read_conn()
    assert rc.execute("SELECT count(*) FROM tests").fetchone()[0] == 1
    with pytest.raises(Exception):
        rc.execute("INSERT INTO tests (id) VALUES (9)")  # query_only
    rc.close()
    # the store's own connection is unaffected
    assert rows(store) == [(1, "a")]


def test_exotic_column_name_rejected():
    with pytest.raises(SchemaError, match="invalid column name"):
        parse_sql('CREATE TABLE t (id INTEGER PRIMARY KEY, "a\'b" TEXT);')


def test_multi_statement_tx_single_version():
    store = mk_store()
    ts = Timestamp.now()
    with store.write_tx(ts) as tx:
        tx.execute("INSERT INTO tests (id, text) VALUES (1, 'a')")
        tx.execute("INSERT INTO tests2 (id, text) VALUES (9, 'z')")
        changes, db_version, last_seq = tx.commit()
    assert db_version == 1
    assert {c.table for c in changes} == {"tests", "tests2"}
    assert [c.seq for c in changes] == list(range(len(changes)))
    assert last_seq == len(changes) - 1


def test_rollback_on_error():
    store = mk_store()
    with pytest.raises(Exception):
        with store.write_tx(Timestamp.now()) as tx:
            tx.execute("INSERT INTO tests (id, text) VALUES (1, 'a')")
            tx.execute("INSERT INTO nonexistent VALUES (1)")
    assert rows(store) == []
    assert store.db_version_for(store.site_id) == 0


# -- remote application + merge rules --------------------------------------


def replicate(src_changes, dst):
    return dst.apply_changes(src_changes)


def test_basic_replication():
    a, b = mk_store(1), mk_store(2)
    changes, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'hello')")
    res = replicate(changes, b)
    assert rows(b) == [(1, "hello")]
    assert len(res.impactful) == len(changes)
    assert res.changed_tables == {"tests": 2}


def test_idempotent_apply():
    a, b = mk_store(1), mk_store(2)
    changes, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'hello')")
    replicate(changes, b)
    res = replicate(changes, b)
    assert res.impactful == []  # crsql_rows_impacted-equivalent: no-op


def test_lww_higher_col_version_wins():
    a, b = mk_store(1), mk_store(2)
    ch1, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'a')")
    replicate(ch1, b)
    ch_b, _, _ = write(b, "UPDATE tests SET text = 'b-wins' WHERE id = 1")
    assert ch_b[0].col_version == 2
    res = replicate(ch_b, a)
    assert rows(a) == [(1, "b-wins")]
    assert len(res.impactful) == 1
    # stale lower col_version loses
    res2 = replicate(ch1, a)
    assert rows(a) == [(1, "b-wins")]
    assert not any(c.cid == "text" for c in res2.impactful)


def test_lww_equal_version_value_tiebreak():
    # concurrent writes with equal col_version: larger value wins everywhere
    a, b = mk_store(1), mk_store(2)
    cha, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'aaa')")
    chb, _, _ = write(b, "INSERT INTO tests (id, text) VALUES (1, 'zzz')")
    replicate(chb, a)
    replicate(cha, b)
    assert rows(a) == rows(b) == [(1, "zzz")]


def test_merge_equal_values_no_impact():
    a, b = mk_store(1), mk_store(2)
    cha, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'same')")
    chb, _, _ = write(b, "INSERT INTO tests (id, text) VALUES (1, 'same')")
    res = replicate(chb, a)
    # sentinel same cl: no-op; text equal value: merged silently
    assert res.impactful == []


def test_delete_beats_concurrent_update():
    a, b = mk_store(1), mk_store(2)
    ch1, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'x')")
    replicate(ch1, b)
    del_b, _, _ = write(b, "DELETE FROM tests WHERE id = 1")  # cl=2
    upd_a, _, _ = write(a, "UPDATE tests SET text = 'y' WHERE id = 1")  # cl=1
    replicate(del_b, a)
    replicate(upd_a, b)
    assert rows(a) == rows(b) == []


def test_resurrection_beats_delete():
    a, b = mk_store(1), mk_store(2)
    ch1, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'x')")
    replicate(ch1, b)
    write(a, "DELETE FROM tests WHERE id = 1")
    res_a, _, _ = write(a, "INSERT INTO tests (id, text) VALUES (1, 'back')")  # cl=3
    del_b, _, _ = write(b, "DELETE FROM tests WHERE id = 1")  # cl=2
    replicate(del_b, a)
    replicate(res_a, b)
    assert rows(a) == [(1, "back")]
    assert rows(b) == [(1, "back")]


def test_convergence_all_orders():
    """Apply three sites' concurrent changesets in every order; all replicas
    converge to the same state."""
    base = mk_store(9)
    ch0, _, _ = write(base, "INSERT INTO tests (id, text) VALUES (1, 'base')")

    sets = []
    for sb, op in [
        (1, ("UPDATE tests SET text = 'alpha' WHERE id = 1", ())),
        (2, ("DELETE FROM tests WHERE id = 1", ())),
        (3, ("INSERT INTO tests (id, text) VALUES (2, 'two')", ())),
    ]:
        s = mk_store(sb)
        replicate(ch0, s)
        chs, _, _ = write(s, *op)
        sets.append(chs)

    results = []
    for perm in itertools.permutations(range(3)):
        r = mk_store(50)
        replicate(ch0, r)
        for i in perm:
            replicate(sets[i], r)
        results.append(rows(r))
    assert all(r == results[0] for r in results), results


def test_pk_only_table_and_blob_pks():
    store = mk_store()
    changes, _, _ = write(
        store, "INSERT INTO testsblob (id, text) VALUES (?, 'v')", (b"\x01\x02",)
    )
    b2 = mk_store(2)
    replicate(changes, b2)
    got = b2._conn.execute("SELECT id, text FROM testsblob").fetchone()
    assert bytes(got[0]) == b"\x01\x02" and got[1] == "v"


# -- serving changes back (crsql_changes reads) ----------------------------


def test_changes_for_versions_roundtrip():
    a, b = mk_store(1), mk_store(2)
    write(a, "INSERT INTO tests (id, text) VALUES (1, 'one')")
    write(a, "INSERT INTO tests (id, text) VALUES (2, 'two')")
    served = list(a.changes_for_versions(a.site_id, 1, 2))
    assert [v for v, _ in served] == [2, 1]  # newest first
    for _, chs in served:
        replicate(chs, b)
    assert rows(b) == [(1, "one"), (2, "two")]


def test_overwritten_version_serves_nothing():
    a = mk_store(1)
    write(a, "INSERT INTO tests (id, text) VALUES (1, 'old')")
    write(a, "UPDATE tests SET text = 'new' WHERE id = 1")
    served = dict(a.changes_for_versions(a.site_id, 1, 2))
    # version 1's text cell was overwritten; only its sentinel remains
    assert [c.cid for c in served.get(1, [])] == [SENTINEL]
    assert [c.cid for c in served[2]] == ["text"]
    assert served[2][0].val == "new"


# -- buffered partials -----------------------------------------------------


def test_buffer_and_drain_partials():
    a, b = mk_store(1), mk_store(2)
    with a.write_tx(Timestamp.now()) as tx:
        for i in range(10):
            tx.execute(f"INSERT INTO tests (id, text) VALUES ({i}, 'v{i}')")
        changes, version, last_seq = tx.commit()
    # deliver out of order, in two buffered halves
    half = len(changes) // 2
    b.buffer_partial_changes(
        a.site_id, version, changes[half:], (changes[half].seq, last_seq),
        last_seq, Timestamp.now(),
    )
    assert b.take_buffered_version(a.site_id, version)[0].seq == changes[half].seq
    b.buffer_partial_changes(
        a.site_id, version, changes[:half], (0, changes[half - 1].seq),
        last_seq, Timestamp.now(),
    )
    buffered = b.take_buffered_version(a.site_id, version)
    assert [c.seq for c in buffered] == list(range(last_seq + 1))
    res = b.apply_changes(buffered)
    assert len(res.impactful) == len(changes)
    b.clear_buffered_version(a.site_id, version)
    assert b.take_buffered_version(a.site_id, version) == []
    assert rows(b) == rows(a)


def test_load_booked_versions_roundtrip():
    a = mk_store(1)
    write(a, "INSERT INTO tests (id, text) VALUES (1, 'x')")
    bv = a.load_booked_versions(a.site_id)
    assert bv.max == 1
    assert a.booked_actor_ids() == [a.site_id]


# -- r3: 12-step column-change table rebuild (schema.rs:528-596)


def test_column_type_change_rebuilds_table(tmp_path):
    store = CrdtStore(str(tmp_path / "r.db"))
    store.apply_schema_sql(
        "CREATE TABLE m (id INTEGER PRIMARY KEY, n TEXT, o TEXT);"
    )
    with store.write_tx(Timestamp.now()) as tx:
        tx.execute("INSERT INTO m (id, n, o) VALUES (1, '42', 'keep')")
        tx.execute("INSERT INTO m (id, n, o) VALUES (2, '7', 'also')")

    # change n's type TEXT -> INTEGER with data present: must rebuild,
    # not refuse, and must keep both the data and the CRDT clock state
    clock_before = store._conn.execute(
        'SELECT COUNT(*) FROM "m__crdt_clock"'
    ).fetchone()[0]
    store.apply_schema_sql(
        "CREATE TABLE m (id INTEGER PRIMARY KEY, n INTEGER, o TEXT);"
    )
    rows = store._conn.execute("SELECT id, n, o FROM m ORDER BY id").fetchall()
    assert [(r["id"], r["n"], r["o"]) for r in rows] == [
        (1, 42, "keep"),
        (2, 7, "also"),
    ]
    clock_after = store._conn.execute(
        'SELECT COUNT(*) FROM "m__crdt_clock"'
    ).fetchone()[0]
    assert clock_after == clock_before  # replication state untouched
    assert store.schema.tables["m"].columns["n"].sql_type.upper() == "INTEGER"

    # writes keep replicating after the rebuild (triggers recreated)
    with store.write_tx(Timestamp.now()) as tx:
        tx.execute("INSERT INTO m (id, n, o) VALUES (3, 9, 'post')")
    assert (
        store._conn.execute(
            'SELECT COUNT(*) FROM "m__crdt_clock"'
        ).fetchone()[0]
        > clock_after
    )
    store.close()


def test_rebuild_with_added_column_and_default(tmp_path):
    store = CrdtStore(str(tmp_path / "r2.db"))
    store.apply_schema_sql("CREATE TABLE m (id INTEGER PRIMARY KEY, a TEXT);")
    with store.write_tx(Timestamp.now()) as tx:
        tx.execute("INSERT INTO m (id, a) VALUES (1, 'x')")
    # change a's default AND add a column in one migration
    store.apply_schema_sql(
        "CREATE TABLE m (id INTEGER PRIMARY KEY, a TEXT DEFAULT 'dflt',"
        " b INTEGER DEFAULT 5);"
    )
    row = store._conn.execute("SELECT a, b FROM m WHERE id = 1").fetchone()
    assert (row["a"], row["b"]) == ("x", 5)
    store._conn.execute("INSERT INTO m (id) VALUES (99)")
    row = store._conn.execute("SELECT a, b FROM m WHERE id = 99").fetchone()
    assert (row["a"], row["b"]) == ("dflt", 5)
    store.close()


def test_corro_json_contains(tmp_path):
    """Custom SQL fn parity (sqlite.rs:237-274) — present on BOTH the
    write connection and read connections (the /v1/queries + pubsub
    paths run user SQL on read conns)."""
    store = CrdtStore(str(tmp_path / "j.db"))
    rconn = store.read_conn()
    assert rconn.execute(
        "SELECT corro_json_contains(?, ?)", ('{"a": 1}', '{"a": 1}')
    ).fetchone()[0] == 1
    rconn.close()
    q = lambda sel, obj: store._conn.execute(
        "SELECT corro_json_contains(?, ?)", (sel, obj)
    ).fetchone()[0]
    assert q('{"a": 1}', '{"a": 1, "b": 2}') == 1
    assert q('{"a": 1, "b": 2}', '{"a": 1}') == 0
    assert q('{"a": {"x": 1}}', '{"a": {"x": 1, "y": 2}, "b": 0}') == 1
    assert q('{"a": {"x": 2}}', '{"a": {"x": 1, "y": 2}}') == 0
    assert q('"s"', '"s"') == 1
    assert q("1", "2") == 0
    assert q("{}", '{"anything": true}') == 1
    import sqlite3 as s3
    import pytest as pt
    with pt.raises(s3.OperationalError):
        q("not json", "{}")
    store.close()


def test_pooled_read_connections(tmp_path):
    """SplitPool read side (agent.rs:478-519): pooled RO conns are reused
    and capped at READ_POOL_MAX; pool drains on close."""
    store = CrdtStore(str(tmp_path / "p.db"))
    store.apply_schema_sql("CREATE TABLE pt (id INTEGER PRIMARY KEY);")
    with store.pooled_read() as c1:
        first = id(c1)
        assert c1.execute("SELECT COUNT(*) FROM pt").fetchone()[0] == 0
    with store.pooled_read() as c2:
        assert id(c2) == first  # reused
    # cap: release more than READ_POOL_MAX and the extras close
    conns = [store.acquire_read() for _ in range(store.READ_POOL_MAX + 3)]
    for c in conns:
        store.release_read(c)
    assert len(store._read_pool) == store.READ_POOL_MAX
    store.close()
    assert not store._read_pool

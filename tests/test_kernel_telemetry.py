"""Device telemetry lane (r7): the [N_EVENTS] counter vector carried in
the SWIM scan state + the CRDT merge kernel's decision counts.

The lane's contract:
  1. it never perturbs the kernel — trajectories with the lane are the
     trajectories without it (same rng stream, pure mask reductions);
  2. both tick formulations count identically where they are the same
     computation — `tick_mode="fused"` vs the r5 reference is BIT-equal
     (events included) once the one semantic difference between them
     (feed staleness) is configured away, and the identity-hash pview
     tick counts exactly what the dense tick counts;
  3. the accounting is internally consistent (emitted = lost +
     delivered + overflowed) and monotone;
  4. it adds no host syncs: the fused tick still lowers to ONE scan and
     the lane drains inside the existing stats readback;
  5. the drivers publish per-window deltas to the shared registry
     (`corro.kernel.events.total`) without double counting.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import swim, swim_pview
from corrosion_tpu.runtime.metrics import (
    CRDT_MERGE_EVENTS,
    KERNEL_EVENTS,
    METRICS,
    Registry,
    kernel_event_totals,
)

EV = {name: i for i, name in enumerate(KERNEL_EVENTS)}


def _run(params, state, ticks, seed=7, module=swim):
    # scanned ticks: one small compile per (params, ticks) bucket — an
    # unrolled per-tick trace at these tick counts is minutes of XLA:CPU
    # compile on the 1-core CI host
    return module.tick_n(state, jax.random.PRNGKey(seed), params, ticks)


# ---------------------------------------------------------------------------
# accounting invariants
# ---------------------------------------------------------------------------


def test_dense_events_accounting_identity_under_loss():
    """emitted = lost + delivered + overflowed, with loss injection on
    and the inbox cap binding (piggyback+antientropy wide sends)."""
    params = swim.SwimParams(n=64, loss=0.1, incoming_slots=8)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    assert int(jnp.sum(jnp.abs(state.events))) == 0  # lane starts clean
    state = _run(params, state, 10)
    ev = np.asarray(state.events)
    assert ev[EV["gossip_emitted"]] > 0
    assert ev[EV["gossip_lost"]] > 0  # loss=0.1 over ~10k messages
    assert ev[EV["inbox_overflowed"]] > 0  # cap 8 < fanout*(piggyback+ae)
    assert (
        ev[EV["gossip_emitted"]]
        == ev[EV["gossip_lost"]]
        + ev[EV["inbox_delivered"]]
        + ev[EV["inbox_overflowed"]]
    )
    assert ev[EV["feed_pulls"]] > 0 and ev[EV["seed_pulls"]] > 0
    assert ev[EV["merge_won"]] > 0
    assert np.all(ev >= 0)


def test_pview_events_accounting_identity():
    params = swim_pview.PViewParams(
        n=128, slots=32, loss=0.05, feeds_per_tick=2, feed_entries=16
    )
    state = swim_pview.init_state(params, jax.random.PRNGKey(0))
    state = _run(params, state, 10, module=swim_pview)
    ev = np.asarray(state.events)
    assert (
        ev[EV["gossip_emitted"]]
        == ev[EV["gossip_lost"]]
        + ev[EV["inbox_delivered"]]
        + ev[EV["inbox_overflowed"]]
    )
    assert ev[EV["gossip_lost"]] > 0
    assert ev[EV["merge_won"]] > 0
    assert np.all(ev >= 0)


def test_suspicion_lifecycle_events_fire():
    """A crash must eventually show up in the lane as suspect_raised +
    down_declared; a restart as refuted (the alive↔suspect↔dead
    transition visibility Lifeguard-style work needs)."""
    params = swim.SwimParams(n=32, suspicion_ticks=3)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    # 20 boot ticks (was 10): every _run in this phase now shares ONE
    # scan-length specialization instead of compiling 10- and 20-tick
    # variants of the same program (r16 budget audit; scan length is a
    # static arg, so each distinct value is a full XLA compile)
    state = _run(params, state, 20)
    state = swim.set_alive(state, 5, False)
    state = _run(params, state, 20, seed=11)
    ev = np.asarray(state.events)
    assert ev[EV["suspect_raised"]] > 0
    assert ev[EV["down_declared"]] > 0
    # restart + more ticks: the lane is cumulative/monotone
    state = swim.set_alive(state, 5, True)
    state = _run(params, state, 20, seed=13)
    ev2 = np.asarray(state.events)
    assert np.all(ev2 >= ev)

    # refutation needs a LIVE member to hear itself suspected at its
    # current incarnation (a restart pre-empts it by bumping inc), so
    # drive it with heavy loss: failed probes suspect live members, the
    # suspect gossip reaches them, they refute
    lossy = swim.SwimParams(n=32, suspicion_ticks=6, loss=0.35)
    st2 = swim.init_state(lossy, jax.random.PRNGKey(2))
    st2 = _run(lossy, st2, 40, seed=17)
    assert np.asarray(st2.events)[EV["refuted"]] > 0


# ---------------------------------------------------------------------------
# the lane counts identically across formulations
# ---------------------------------------------------------------------------


def test_fused_tick_bit_equal_r5_with_feeds_disabled():
    """With the feed/seed exchange off, "fused" and "r5" are the SAME
    computation (the restructure only changes feed-read staleness) — so
    the whole state INCLUDING the telemetry lane must be bit-identical.
    This is the exactness half of the fused↔r5 telemetry parity pin;
    the with-feeds half is statistical (test_swim_pview.py)."""
    mk = lambda tm: swim_pview.PViewParams(  # noqa: E731
        n=128, slots=32, feed_entries=0, loss=0.05, tick_mode=tm
    )
    sf = swim_pview.init_state(mk("fused"), jax.random.PRNGKey(0))
    sr = swim_pview.init_state(mk("r5"), jax.random.PRNGKey(0))
    for i in range(12):
        key = jax.random.fold_in(jax.random.PRNGKey(3), i)
        if i == 6:  # exercise the suspicion lanes too
            sf = swim_pview.set_alive(sf, 9, False)
            sr = swim_pview.set_alive(sr, 9, False)
        sf = swim_pview.tick(sf, key, mk("fused"))
        sr = swim_pview.tick(sr, key, mk("r5"))
    for name, a in sf._asdict().items():
        assert jnp.array_equal(a, getattr(sr, name)), f"field {name}"
    assert int(np.asarray(sf.events)[EV["gossip_emitted"]]) > 0


def test_identity_hash_pview_events_equal_dense():
    """In the dense-equivalence configuration (slots == n, identity
    hash, r5/pick) the pview tick IS the dense tick — so the two lanes
    must agree event for event, tick for tick."""
    n = 48
    dp = swim.SwimParams(
        n=n, feeds_per_tick=2, feed_entries=16, announce_period=8,
        antientropy=2, gossip_mode="pick", loss=0.1,
    )
    pp = swim_pview.PViewParams(
        n=n, slots=n, identity_hash=True, feeds_per_tick=2,
        feed_entries=16, announce_period=8, antientropy=2,
        tick_mode="r5", gossip_mode="pick", loss=0.1,
    )
    ds = swim.init_state(dp, jax.random.PRNGKey(0))
    ps = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    for i in range(15):
        key = jax.random.fold_in(jax.random.PRNGKey(7), i)
        if i == 5:
            ds = swim.set_alive(ds, 5, False)
            ps = swim_pview.set_alive(ps, 5, False)
        ds = swim.tick(ds, key, dp)
        ps = swim_pview.tick(ps, key, pp)
        assert jnp.array_equal(ds.events, ps.events), (
            i,
            dict(zip(KERNEL_EVENTS, np.asarray(ds.events))),
            dict(zip(KERNEL_EVENTS, np.asarray(ps.events))),
        )


# ---------------------------------------------------------------------------
# zero extra host syncs
# ---------------------------------------------------------------------------


def test_fused_tick_still_lowers_to_one_scan():
    """The acceptance pin: the telemetry lane AND the r8 flight ring
    (enabled at its default size here) ride the scan carry — the jaxpr
    of the scanned fused tick contains exactly ONE scan (and no
    while/cond smuggled in by the lanes)."""
    params = swim_pview.PViewParams(n=64, slots=16, feeds_per_tick=2,
                                    feed_entries=8)
    assert params.ring_ticks > 0  # the pin must cover the ring write
    state = swim_pview.init_state(params, jax.random.PRNGKey(0))
    jaxpr = jax.make_jaxpr(
        lambda s, r: swim_pview._tick_n_impl(s, r, params, 4)
    )(state, jax.random.PRNGKey(1))
    text = str(jaxpr)
    assert text.count("scan[") == 1, "fused tick no longer one scan"
    assert "while[" not in text
    # and the ring is genuinely written INSIDE that one scan
    out = swim_pview.tick_n(state, jax.random.PRNGKey(1), params, 4)
    assert int(jnp.sum(jnp.abs(out.ring))) > 0

    # dense kernel: same contract
    dparams = swim.SwimParams(n=64)
    dstate = swim.init_state(dparams, jax.random.PRNGKey(0))
    dtext = str(
        jax.make_jaxpr(
            lambda s, r: swim._tick_n_impl(s, r, dparams, 4)
        )(dstate, jax.random.PRNGKey(1))
    )
    assert dtext.count("scan[") == 1


def test_stats_and_events_single_readback_and_uint32_wrap():
    """stats_and_events returns the lane AND the flight ring beside the
    stats; a lane that wrapped mod 2^32 on device still yields correct
    uint32 deltas."""
    params = swim.SwimParams(n=32)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    state = swim.tick(state, jax.random.PRNGKey(1), params)
    stats, ev, fl = swim.stats_and_events(state)
    assert set(stats) == {"coverage", "detected", "false_positive"}
    assert ev.dtype == np.uint32 and ev.shape == (swim.N_EVENTS,)
    # the ring drains in the same readback (r8): raw rows + the tick
    assert fl.t == 1
    assert fl.ring.shape == (params.ring_ticks, swim.N_FLIGHT_LANES)
    assert np.array_equal(fl.ring[0, : swim.N_EVENTS], np.asarray(ev))

    # wrap math: device totals are int32 two's complement; a prev
    # snapshot near the top of the range subtracts wrap-safe
    prev = np.array([0xFFFF_FFF0], dtype=np.uint32)
    cur = np.array([16], dtype=np.uint32)  # wrapped past 2^32
    assert int((cur - prev).astype(np.uint32)[0]) == 32


# ---------------------------------------------------------------------------
# driver publishing + the CRDT merge kernel's lane
# ---------------------------------------------------------------------------


def test_cluster_sims_publish_registry_deltas():
    from corrosion_tpu.models.cluster import PViewClusterSim

    reg = Registry()
    import corrosion_tpu.models.cluster as cluster_mod

    # publish into a scratch registry: the assertion is on deltas, which
    # the process-global registry (other tests) would pollute
    orig = cluster_mod.record_kernel_events
    cluster_mod.record_kernel_events = (
        lambda kernel, deltas: orig(kernel, deltas, registry=reg)
    )
    try:
        sim = PViewClusterSim(128, slots=32, feeds_per_tick=2,
                              feed_entries=16)
        sim.step(5)
        sim.stats()
        totals1 = kernel_event_totals(reg)["pview"]
        device_now = np.asarray(jax.device_get(sim.state.events))
        for name, i in EV.items():
            if device_now[i]:
                assert totals1[name] == float(device_now[i]), name
        # draining again without stepping must add nothing
        sim.stats()
        assert kernel_event_totals(reg)["pview"] == totals1
        # stepping again adds exactly the new window
        sim.step(3)
        sim.stats()
        totals2 = kernel_event_totals(reg)["pview"]
        device_after = np.asarray(jax.device_get(sim.state.events))
        for name, i in EV.items():
            if device_after[i]:
                assert totals2[name] == float(device_after[i]), name
    finally:
        cluster_mod.record_kernel_events = orig


def test_crdt_merge_kernel_publishes_decision_events(monkeypatch):
    """The array engine's decisions surface as
    corro.kernel.events.total{kernel="crdt_merge"} increments, counted
    on-device and drained with the decision readback."""
    import random

    from tests.test_crdt_batch import mk_store, random_changes

    monkeypatch.setenv("CORRO_CRDT_ENGINE", "array")
    before = kernel_event_totals(METRICS).get("crdt_merge", {})
    b_won = before.get("decide_won", 0.0)
    b_stale = before.get("decide_stale", 0.0)
    store = mk_store()
    changes = random_changes(random.Random(99), 60)
    res = store.apply_changes(changes)
    store.close()
    assert res is not None
    after = kernel_event_totals(METRICS)["crdt_merge"]
    won = after.get("decide_won", 0.0) - b_won
    stale = after.get("decide_stale", 0.0) - b_stale
    # the store may pre-filter already-known changes before the kernel
    # sees a batch, so <= holds, not ==; both outcome classes must have
    # been counted for a random workload this size
    assert won > 0 and stale > 0
    assert won + stale <= len(changes)


def test_event_tables_are_canonical():
    """The single-source-of-truth tables the kernels, sims, status plane
    and report all key on."""
    assert len(KERNEL_EVENTS) == swim.N_EVENTS
    assert len(set(KERNEL_EVENTS)) == len(KERNEL_EVENTS)
    assert len(set(CRDT_MERGE_EVENTS)) == len(CRDT_MERGE_EVENTS) == 4
    with pytest.raises(ValueError):
        swim._event_vector(nonsense=jnp.int32(1), **{
            n: jnp.int32(0) for n in KERNEL_EVENTS
        })

"""Replay-gate provenance: a banked TPU bench record is only replayable
when its capture-time code fingerprint matches the tree exactly.

r4 verdict: the driver-facing headline was a replay with
``code_sha_missing`` — a TPU-labeled number that could not be tied to a
code version.  The gate in ``bench._stored_tpu_record`` now rejects
sha-less and sha-drifted records outright (the live number, even CPU, is
the honest one), and ``bench.child_main`` stamps the fingerprint at run
START so the sha describes the code actually imported and measured.
"""

from __future__ import annotations

import json
import os

import pytest

import bench


def _record(n: int, **detail_overrides) -> dict:
    detail = {
        "n_members": n,
        "coverage": 1.0,
        "false_positive": 0.0,
        "stable_tick": 50,
        "feeds_per_tick": 4,
        "feed_entries": 125,
        "seed_mode": "fingers",
        "record_every": 25,
        "coverage_target": 0.999,
        "inbox_impl": "gsort",
        "gossip_mode": "shift",  # the kernel default since the r5 flip
        "platform": "tpu",
        "measured_at": "2026-07-31 14:00:00",
        "code_sha": bench._code_fingerprint(),
    }
    detail.update(detail_overrides)
    return {
        "metric": f"time_to_stable_membership_n{n}",
        "value": 0.5,
        "unit": "s",
        "vs_baseline": 120.0,
        "detail": detail,
    }


@pytest.fixture()
def banked(tmp_path, monkeypatch):
    """Redirect the banked-record path into a tempdir; return a writer.

    Only the record path is patched — ``_code_fingerprint`` keeps
    hashing the real tree, so the sha-match test exercises real-hash
    comparison rather than a degenerate all-"missing" dict.
    """
    monkeypatch.setattr(
        bench, "_banked_record_path",
        lambda n: str(tmp_path / f"BENCH_TPU_{n // 1000}k.json"),
    )
    for var in ("BENCH_FEEDS", "BENCH_SEED_MODE", "BENCH_RECORD_EVERY",
                "BENCH_COVERAGE", "BENCH_INBOX_IMPL", "BENCH_GOSSIP_MODE"):
        monkeypatch.delenv(var, raising=False)

    def write(n: int, rec: dict) -> None:
        with open(tmp_path / f"BENCH_TPU_{n // 1000}k.json", "w") as f:
            f.write(json.dumps(rec) + "\n")

    return write


def test_sha_matched_record_replays(banked):
    banked(2000, _record(2000))
    rec, reason = bench._stored_tpu_record(2000)
    assert reason is None
    assert rec is not None
    assert rec["detail"]["replayed_from"]["file"] == "BENCH_TPU_2k.json"
    assert rec["detail"]["replayed_from"]["measured_at"] == "2026-07-31 14:00:00"


def test_sha_less_record_rejected(banked):
    rec_in = _record(2000)
    del rec_in["detail"]["code_sha"]
    banked(2000, rec_in)
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None
    assert reason == "replay-rejected:code-sha-missing"


def test_drifted_record_rejected(banked):
    sha = dict(bench._code_fingerprint())
    sha["corrosion_tpu/ops/swim.py"] = "deadbeef0000"
    banked(2000, _record(2000, code_sha=sha))
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None
    assert reason == "replay-rejected:code-drift:corrosion_tpu/ops/swim.py"


def test_workload_mismatch_rejected(banked):
    banked(2000, _record(2000, feeds_per_tick=2))
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None
    assert reason == "replay-rejected:workload-mismatch"


def test_stored_convergence_failure_rejected(banked):
    banked(2000, _record(2000, stable_tick=None))
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None
    assert reason == "replay-rejected:stored-convergence-failure"


def test_measured_at_missing_rejected(banked):
    rec_in = _record(2000)
    del rec_in["detail"]["measured_at"]
    banked(2000, rec_in)
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None
    assert reason == "replay-rejected:measured-at-missing"


def test_fingerprints_are_real_hashes(banked):
    sha = bench._code_fingerprint()
    assert all(v != "missing" for v in sha.values()), sha


def test_no_banked_file(banked):
    rec, reason = bench._stored_tpu_record(2000)
    assert rec is None and reason is None

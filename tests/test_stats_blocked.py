"""Blocked stats passes vs their whole-view/whole-table references.

Both kernels stream their stats reductions over row blocks so the
temporaries stay [B, N]/[B, K] no matter how big the state is (the
whole-view forms OOMed an 80k dense run and crashed the 512k pview
remote compile — PROFILE.md "80k dense OOM" / "the tunnel's
device-execution-time limit").  These tests pin the blocked passes to
the straightforward whole-state formulations they replaced, on shapes
that force multi-block paths with a CLAMPED, overlapping last block,
and on states that exercise every lane (live/dead members, suspect and
down entries, self diagonals).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import swim, swim_pview


def _dense_reference(view, alive):
    """The pre-blocking whole-view formulation of swim._stats_impl."""
    n = view.shape[0]
    af = np.asarray(alive, dtype=np.float32)
    prec = np.asarray(swim.key_prec(view))
    known = np.asarray(swim.key_known(view))
    n_alive = af.sum()
    row_ka = np.where(known & (prec == swim.PREC_ALIVE), af[None, :], 0.0).sum(1)
    row_td = np.where(
        known & (prec == swim.PREC_DOWN), 1.0 - af[None, :], 0.0
    ).sum(1)
    row_fp = np.where(
        known & (prec >= swim.PREC_SUSPECT), af[None, :], 0.0
    ).sum(1)
    cov_num = (row_ka * af).sum() - n_alive  # minus the alive diagonal
    det_num = (row_td * af).sum()
    fp_num = (row_fp * af).sum()
    n_alive_pairs = max(n_alive * (n_alive - 1.0), 1.0)
    n_dead_pairs = max(n_alive * (n - n_alive), 1.0)
    return np.array(
        [cov_num / n_alive_pairs, det_num / n_dead_pairs, fp_num / n_alive_pairs],
        dtype=np.float32,
    )


@pytest.fixture
def block64(monkeypatch):
    """Shrink both kernels' stats block sizes to 64 for the duration of
    a test, clearing the compiled stats traces on BOTH sides of it.

    The clear-before makes the patched global take effect (the jitted
    fns may hold traces compiled at the default block size for these
    shapes).  The clear-AFTER is the leak fix (ADVICE): without it,
    block-64 compiled traces for these (n, slots) shapes outlive the
    monkeypatch — any later compile request for the same shapes would
    silently reuse a stats pass whose block size no longer matches the
    restored globals.  Scoped clears: jax.clear_caches() would evict
    every compiled kernel in the session."""
    monkeypatch.setattr(swim, "_STATS_BLOCK", 64)
    monkeypatch.setattr(swim_pview, "_STATS_BLOCK_ROWS", 64)
    swim._stats_impl.clear_cache()
    swim_pview._stats_impl.clear_cache()
    yield
    swim._stats_impl.clear_cache()
    swim_pview._stats_impl.clear_cache()


@pytest.mark.parametrize("n", [96, 193])
def test_dense_stats_match_whole_view_reference(block64, n):
    # block far smaller than n and NOT dividing it: the final block
    # clamps and overlaps, exercising the fresh-row dedupe mask
    params = swim.SwimParams(n=n)
    state = swim.init_state(params, jax.random.PRNGKey(0), 3, "fingers")
    rng = jax.random.PRNGKey(1)
    for _ in range(6):
        rng, key = jax.random.split(rng)
        state = swim.tick(state, key, params)
    # kill a handful mid-run so DOWN/suspect entries and dead subjects
    # appear; more ticks let suspicion propagate
    for m in (1, n // 2, n - 5):
        state = swim.set_alive(state, m, False)
    for _ in range(10):
        rng, key = jax.random.split(rng)
        state = swim.tick(state, key, params)

    got = np.asarray(jax.device_get(swim._stats_impl(state.view, state.alive)))
    want = _dense_reference(np.asarray(state.view), np.asarray(state.alive))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def _pview_reference(params, packed, alive, t):
    """The pre-blocking whole-table formulation of swim_pview._stats_impl."""
    n = params.n
    af = np.asarray(alive, dtype=np.float32)
    n_alive = max(af.sum(), 1.0)
    rows = np.arange(n, dtype=np.int32)[:, None]
    subj, key = swim_pview._unpack(params, jnp.asarray(packed), rows, t)
    subj, key = np.asarray(subj), np.asarray(key)
    occupied = key > 0
    prec = np.asarray(swim_pview.key_prec(jnp.asarray(key)))
    live_obs = np.asarray(alive)[:, None]
    subj_alive = np.asarray(alive)[np.clip(subj, 0, n - 1)]
    ka = occupied & (prec == swim_pview.PREC_ALIVE) & live_obs & (subj != rows)
    indeg = np.zeros(n, dtype=np.int64)
    np.add.at(indeg, np.where(ka, subj, 0), ka.astype(np.int64))
    total = (ka & subj_alive).sum(dtype=np.float64)
    expected = total / n_alive
    min_in = indeg[np.asarray(alive)].min()
    pv_cov = (
        np.where(np.asarray(alive), indeg >= expected * 0.5, False).sum() / n_alive
    )
    fp_entries = occupied & (prec >= swim_pview.PREC_SUSPECT) & live_obs & subj_alive
    fp = fp_entries.sum() / max(af.sum() * (n_alive - 1), 1.0)
    occ = (occupied & live_obs).sum() / (n_alive * params.slots)
    stale = occupied & (prec == swim_pview.PREC_ALIVE) & live_obs & ~subj_alive
    stale_per = np.zeros(n, dtype=np.int64)
    np.add.at(stale_per, np.where(stale, subj, 0), stale.astype(np.int64))
    dead = ~np.asarray(alive)
    n_dead = dead.sum()
    detected = (
        (dead & (stale_per == 0)).sum() / max(n_dead, 1) if n_dead else 1.0
    )
    return np.array(
        [pv_cov, expected, float(min_in), occ, fp, detected], dtype=np.float32
    )


@pytest.mark.parametrize("n,slots", [(193, 64), (520, 96)])
def test_pview_stats_match_whole_table_reference(block64, n, slots):
    params = swim_pview.PViewParams(
        n=n, slots=slots, feeds_per_tick=4, feed_entries=16
    )
    state = swim_pview.init_state(params, jax.random.PRNGKey(0), seed_mode="fingers")
    rng = jax.random.PRNGKey(1)
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick(state, key, params)
    kills = np.random.RandomState(0).choice(n, max(2, n // 40), replace=False)
    state = swim_pview.set_alive_many(state, kills, False)
    for _ in range(10):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick(state, key, params)

    got = np.asarray(
        jax.device_get(
            swim_pview._stats_impl(params, state.slot_packed, state.alive, state.t)
        )
    )
    want = _pview_reference(
        params, np.asarray(state.slot_packed), np.asarray(state.alive), state.t
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

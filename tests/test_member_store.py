"""Member-state persistence, resurrection and bootstrap fallback
(broadcast/mod.rs:814-949, util.rs:74-179, bootstrap.rs:29-50)."""

import asyncio
import random
from collections import deque
from types import SimpleNamespace

from corrosion_tpu.agent.member_store import (
    _state_from_json,
    _state_json,
    diff_member_states,
    load_member_states,
    snapshot_membership,
    stored_bootstrap_addrs,
)
from corrosion_tpu.agent.members import Members
from corrosion_tpu.agent.membership import Membership, SwimConfig
from corrosion_tpu.net.gossip_codec import MemberState
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.store.crdt import CrdtStore
from corrosion_tpu.types.actor import Actor, ActorId
from corrosion_tpu.types.base import Timestamp


def mk_actor(i: int) -> Actor:
    return Actor(
        id=ActorId(bytes([i]) * 16),
        addr=f"10.0.0.{i}:7000",
        ts=Timestamp.from_unix(i),
    )


def mk_agent():
    net = MemNetwork()
    me = mk_actor(1)
    ms = Membership(me, net.transport(me.addr), SwimConfig(), random.Random(1))
    store = CrdtStore(":memory:")
    return SimpleNamespace(
        membership=ms,
        members=Members(),
        store=store,
        actor_id=me.id,
        cluster_id=me.cluster_id,
    )


def test_state_json_roundtrip():
    actor = mk_actor(3)
    text = _state_json(actor, 7, MemberState.SUSPECT)
    got = _state_from_json(text)
    assert got == (actor, 7, MemberState.SUSPECT)
    assert _state_from_json("{bad json") is None
    assert _state_from_json('{"id": "nope"}') is None


def test_diff_persists_upserts_and_deletes():
    agent = mk_agent()
    a2, a3 = mk_actor(2), mk_actor(3)
    agent.membership.apply_many(
        [(a2, 0, MemberState.ALIVE), (a3, 2, MemberState.SUSPECT)]
    )
    agent.members.rtts["10.0.0.2:7000"] = deque([4.2, 9.9])

    snap = diff_member_states(agent, {})
    rows = agent.store._conn.execute(
        "SELECT actor_id, address, foca_state, rtt_min FROM __corro_members"
        " ORDER BY address"
    ).fetchall()
    assert len(rows) == 2
    assert rows[0]["address"] == "10.0.0.2:7000"
    assert rows[0]["rtt_min"] == 4.2
    assert _state_from_json(rows[1]["foca_state"])[1] == 2  # incarnation

    # unchanged second pass: no-op, same snapshot
    snap2 = diff_member_states(agent, snap)
    assert snap2 == snap

    # member 3 goes down -> excluded from snapshot -> row deleted
    agent.membership.apply_many([(a3, 3, MemberState.DOWN)])
    diff_member_states(agent, snap2)
    rows = agent.store._conn.execute(
        "SELECT address FROM __corro_members"
    ).fetchall()
    assert [r["address"] for r in rows] == ["10.0.0.2:7000"]


def test_load_and_bootstrap_fallback():
    agent = mk_agent()
    actors = [mk_actor(i) for i in (2, 3, 4)]
    agent.membership.apply_many([(a, 1, MemberState.ALIVE) for a in actors])
    diff_member_states(agent, {})

    loaded = load_member_states(agent.store)
    assert sorted(a.addr for a, _, _ in loaded) == [
        "10.0.0.2:7000",
        "10.0.0.3:7000",
        "10.0.0.4:7000",
    ]
    assert all(inc == 1 and st == MemberState.ALIVE for _, inc, st in loaded)

    addrs = stored_bootstrap_addrs(agent.store, count=2)
    assert len(addrs) == 2
    assert set(addrs) <= {a.addr for a in actors}


def test_restart_resurrects_membership():
    """A restarted node (same db) re-applies persisted members before any
    gossip arrives — it remembers the cluster (util.rs:74-111)."""
    agent = mk_agent()
    actors = [mk_actor(i) for i in (2, 3)]
    agent.membership.apply_many([(a, 0, MemberState.ALIVE) for a in actors])
    diff_member_states(agent, {})
    assert agent.membership.cluster_size == 3

    # "restart": fresh membership, same store
    agent2 = mk_agent()
    agent2.store = agent.store
    assert agent2.membership.cluster_size == 1
    states = load_member_states(agent2.store)
    agent2.membership.apply_many(
        [
            s
            for s in states
            if s[0].id != agent2.actor_id
            and s[0].cluster_id == agent2.cluster_id
        ]
    )
    assert agent2.membership.cluster_size == 3
    assert snapshot_membership(agent2) == snapshot_membership(agent)

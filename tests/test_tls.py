"""Gossip-plane TLS/mTLS (VERDICT r2 missing #2).

The reference requires rustls on the gossip plane with optional mTLS
client verification (`klukai-agent/src/api/peer/mod.rs:152-373`) and
plaintext only as an explicit opt-in (`quinn_plaintext.rs:23-35`). These
tests pin: all three lanes work over TLS (datagrams ride the encrypted
D-lane — no plaintext UDP socket exists in TLS mode), an mTLS server
rejects clients without a CA-signed cert, plaintext remains the explicit
default, and two full agents gossip + replicate over a TLS transport.
"""

from corrosion_tpu.runtime.tmpdb import fresh_db_path
import asyncio
import ssl

import pytest

# the whole module drives cert generation through corrosion_tpu.tls,
# which needs the optional `cryptography` package — skip cleanly (not a
# collection error) on images without it
pytest.importorskip(
    "cryptography",
    reason="gossip-plane TLS needs the optional `cryptography` package",
)

from corrosion_tpu import tls
from corrosion_tpu.net.tcp import TcpListener, TcpTransport
from corrosion_tpu.runtime.config import Config, GossipTlsConfig


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    ca_cert, ca_key = str(d / "ca.pem"), str(d / "ca.key")
    tls.generate_ca(ca_cert, ca_key)
    tls.generate_server_cert(
        ca_cert, ca_key, "127.0.0.1", str(d / "srv.pem"), str(d / "srv.key")
    )
    tls.generate_client_cert(
        ca_cert, ca_key, str(d / "cli.pem"), str(d / "cli.key")
    )
    # a second, UNRELATED CA + client cert for the rejection test
    ca2_cert, ca2_key = str(d / "ca2.pem"), str(d / "ca2.key")
    tls.generate_ca(ca2_cert, ca2_key)
    tls.generate_client_cert(
        ca2_cert, ca2_key, str(d / "rogue.pem"), str(d / "rogue.key")
    )
    return d


def tls_cfg(certs, mtls=False, client_cert=True, rogue=False):
    return GossipTlsConfig(
        cert_file=str(certs / "srv.pem"),
        key_file=str(certs / "srv.key"),
        ca_file=str(certs / "ca.pem"),
        mtls=mtls,
        client_cert_file=(
            str(certs / ("rogue.pem" if rogue else "cli.pem"))
            if client_cert
            else None
        ),
        client_key_file=(
            str(certs / ("rogue.key" if rogue else "cli.key"))
            if client_cert
            else None
        ),
    )


def test_tls_three_lanes(certs):
    async def main():
        server_ctx, client_ctx = tls.build_ssl_contexts(tls_cfg(certs))
        got = {"dgram": asyncio.Event(), "uni": asyncio.Event(), "data": {}}

        async def on_datagram(src, data):
            got["data"]["dgram"] = data
            got["dgram"].set()

        async def on_uni(src, data):
            got["data"].setdefault("uni", []).append(data)
            got["uni"].set()

        async def on_bi(stream):
            frame = await stream.recv()
            await stream.send(b"pong:" + frame)
            await stream.finish()

        server = await TcpListener.bind(ssl_context=server_ctx)
        server.serve(on_datagram, on_uni, on_bi)
        # TLS mode: NO plaintext UDP socket exists
        assert server._udp_transport is None

        client_listener = await TcpListener.bind(ssl_context=server_ctx)
        client_listener.serve(on_datagram, on_uni, on_bi)
        t = TcpTransport(client_listener, ssl_context=client_ctx)

        await t.send_datagram(server.addr, b"dg")
        await asyncio.wait_for(got["dgram"].wait(), 5)
        assert got["data"]["dgram"] == b"dg"

        await t.send_uni(server.addr, b"frame1")
        await t.send_uni(server.addr, b"frame2")
        await asyncio.wait_for(got["uni"].wait(), 5)
        for _ in range(50):
            if len(got["data"].get("uni", [])) == 2:
                break
            await asyncio.sleep(0.01)
        assert got["data"]["uni"] == [b"frame1", b"frame2"]

        bi = await t.open_bi(server.addr)
        await bi.send(b"syn")
        assert await bi.recv() == b"pong:syn"
        bi.close()

        await t.close()
        await server.close()
        await client_listener.close()

    asyncio.run(main())


def test_mtls_rejects_unknown_client(certs):
    async def main():
        server_ctx, _ = tls.build_ssl_contexts(tls_cfg(certs, mtls=True))
        seen = asyncio.Event()

        async def handler(*a):
            seen.set()

        server = await TcpListener.bind(ssl_context=server_ctx)
        server.serve(handler, handler, handler)
        host, port = server.addr.rsplit(":", 1)

        async def attempt(client_ctx) -> bool:
            """True if the server accepted and processed our frame.

            With TLS 1.3 the server's client-cert rejection arrives only
            AFTER the client's handshake returns, so the proof of
            rejection is behavioral: the connection dies without any
            handler ever running."""
            seen.clear()
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(
                        host, int(port), ssl=client_ctx, server_hostname=host
                    ),
                    5,
                )
            except (ssl.SSLError, ConnectionError, OSError):
                return False
            try:
                writer.write(b"U" + b"\x00\x00\x00\x05sneak")
                await writer.drain()
                # a rejecting server alert terminates the stream promptly;
                # an accepting server keeps the uni lane open (read blocks)
                await asyncio.wait_for(reader.read(), 1.5)
            except asyncio.TimeoutError:
                pass  # connection stayed open — acceptance path
            except (ssl.SSLError, ConnectionError, OSError):
                pass
            finally:
                writer.close()
            await asyncio.sleep(0.2)
            return seen.is_set()

        # cert from an unrelated CA → rejected
        _, rogue_ctx = tls.build_ssl_contexts(
            tls_cfg(certs, mtls=True, rogue=True)
        )
        assert not await attempt(rogue_ctx), "rogue client was accepted"

        # no client cert at all → rejected
        _, nocert_ctx = tls.build_ssl_contexts(
            tls_cfg(certs, mtls=True, client_cert=False)
        )
        assert not await attempt(nocert_ctx), "certless client was accepted"

        # the legit client still gets through
        _, good_ctx = tls.build_ssl_contexts(tls_cfg(certs, mtls=True))
        assert await attempt(good_ctx), "legit mTLS client was rejected"

        await server.close()

    asyncio.run(main())


def test_plaintext_is_explicit_default():
    cfg = Config()
    assert cfg.gossip.plaintext is True
    assert cfg.gossip.tls_enabled is False


def test_plaintext_off_without_certs_fails_loudly(tmp_path):
    """plaintext=false with a broken/missing [gossip.tls] must raise at
    setup — never silently fall back to an unencrypted gossip plane."""
    from corrosion_tpu.agent.run import setup

    async def main():
        cfg = Config()
        cfg.db.path = fresh_db_path()
        cfg.gossip.bind_addr = "127.0.0.1:0"
        cfg.gossip.plaintext = False  # no tls section configured
        with pytest.raises(ValueError, match="cert_file"):
            await setup(cfg)

    asyncio.run(main())


def test_two_agents_replicate_over_tls(certs):
    """Full-stack: two agents on loopback TLS transports gossip membership
    and replicate a row (the two-node DevCluster-over-TLS proof)."""
    from tests.test_agent import (
        TEST_SCHEMA,
        FAST_SWIM,
        count_rows,
        fast_config,
        insert,
        wait_until,
    )
    from corrosion_tpu.agent.run import run, setup, shutdown

    from tests.test_agent import free_port

    async def main():
        cfg_tls = tls_cfg(certs)
        agents = []
        addrs = [f"127.0.0.1:{free_port()}" for _ in range(2)]
        for i, addr in enumerate(addrs):
            cfg = fast_config(addr, bootstrap=[a for a in addrs if a != addr])
            cfg.gossip.plaintext = False
            cfg.gossip.tls = cfg_tls
            agent = await setup(cfg, network=None)
            agent.membership.config = FAST_SWIM
            agent.store.apply_schema_sql(TEST_SCHEMA)
            await run(agent)
            agents.append(agent)

        a, b = agents
        assert await wait_until(
            lambda: len(a.members.states) >= 1 and len(b.members.states) >= 1
        ), "TLS agents never saw each other"
        await insert(a, 1, "tls-row")
        assert await wait_until(lambda: count_rows(b) == 1), (
            "row did not replicate over TLS"
        )
        for agent in agents:
            await shutdown(agent)

    asyncio.run(main())

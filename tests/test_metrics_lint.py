"""Metric-name drift gate: the COMPONENTS.md observability table must
match the tree's `*.counter/gauge/histogram` call sites exactly (both
directions) — see `scripts/lint_metrics.py`.  Running it as a tier-1
test is what makes the table an inventory rather than documentation."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import lint_metrics  # noqa: E402


def test_no_metric_name_drift():
    problems = lint_metrics.lint()
    assert problems == [], "\n".join(problems)


def test_table_is_nonempty_and_deduped():
    names = lint_metrics.parse_components_table()
    # the r7 additions must be present by name — the lane the status
    # plane and obs_report render
    assert "corro.kernel.events.total" in names
    assert "corro.kernel.phase.seconds" in names
    assert len(names) == len(set(names))
    assert len(names) > 100  # the full inventory, not a stub


def test_scanner_sees_known_call_sites():
    literals, wildcards = lint_metrics.scan_call_sites()
    # a multiline call site (name on the continuation line) must be seen
    assert "corro.agent.changes.queued.seconds" in literals
    # the write-gate f-string site surfaces as a wildcard
    assert any("write_gate" in w for w in wildcards)

"""r12 cluster observatory, live (tier-1).

Runs the shared 3-node scenario harness
(`models/cluster.py::cluster_observatory_scenario`) whose internal pins
carry the acceptance contract: full digest coverage on every node,
cluster-merged stage percentiles EQUAL to the merge of the per-node
local histograms (counts scale by node count, quantiles are identical —
served over HTTP `GET /v1/cluster` on one node), a mem-net partition
flagged by the view-divergence detector within a bounded number of
digest rounds, and exactly ONE flight-recorder incident dump per
divergence episode.  The unit half (codec, freshest-wins, episode state
machine) lives in tests/test_digest.py; the banked detection baseline
(`scripts/chaos_soak.py --phase cluster`) is guarded against drift
below.
"""

from __future__ import annotations

import asyncio
import json
import os

# detection must land within this many digest rounds of the fault —
# silence threshold (silent_after_mult=3) + divergence_checks (2) plus
# generous slack for a descheduled 1-core host
DETECT_ROUNDS_BOUND = 20


def _run(scenario: str, seed: int, **kw) -> dict:
    from corrosion_tpu.models.cluster import cluster_observatory_scenario

    return asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(
            cluster_observatory_scenario(scenario, seed=seed, **kw), 240
        )
    )


def test_cluster_observatory_quiet_exact_aggregation(tmp_path, monkeypatch):
    """Quiet 3-node cluster: any-node /v1/cluster coverage + EXACT
    aggregation (pinned inside the harness), zero divergence episodes,
    zero incident dumps."""
    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    out = _run("quiet", seed=31)
    assert out["coverage"]["fresh"] == 3
    assert not out["divergence_quiet"]
    assert not list(tmp_path.glob("*cluster_divergence*"))


def test_cluster_observatory_partition_detected_once(tmp_path, monkeypatch):
    """An injected mem-net partition opens exactly one divergence
    episode per observing node within the round bound, dumps exactly
    one incident per episode, and clears after heal."""
    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    out = _run("partition", seed=32)
    assert out["detect_rounds"] <= DETECT_ROUNDS_BOUND, out
    assert out["heal_rounds"] is not None
    # every node observed the partition exactly once (the cut node sees
    # the other two silent; they see it silent)
    assert set(out["episodes"].values()) == {1}, out["episodes"]
    dumps = list(tmp_path.glob("*cluster_divergence*"))
    assert len(dumps) == out["episodes_total"], (
        f"{len(dumps)} dumps for {out['episodes_total']} episodes"
    )
    # each dump holds a non-empty kernel="cluster" divergence timeline
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert any(
        fr.get("kernel") == "cluster" for fr in dump.get("frames", [])
    ), "incident dump carries no cluster divergence frames"


def test_cluster_obs_banked_record_holds_acceptance():
    """Drift guard on CLUSTER_OBS.json (`scripts/chaos_soak.py --phase
    cluster` re-banks): all three scenarios present, partition/churn
    detected within the round bound with one dump per episode, quiet
    clean."""
    path = os.path.join(os.path.dirname(__file__), "..", "CLUSTER_OBS.json")
    with open(path) as f:
        record = json.load(f)
    scen = record["scenarios"]
    assert set(scen) == {"quiet", "partition", "churn"}
    assert scen["quiet"].get("episodes_total", 0) == 0
    assert scen["quiet"]["incident_dumps"] == 0
    assert scen["quiet"]["coverage"]["fresh"] == scen["quiet"]["nodes"]
    for name in ("partition", "churn"):
        s = scen[name]
        assert 1 <= s["detect_rounds"] <= DETECT_ROUNDS_BOUND, (name, s)
        assert s["heal_rounds"] >= 1, (name, s)
        assert s["incident_dumps"] == s["episodes_total"] > 0, (name, s)
        assert s["timeline"], f"{name}: no divergence timeline banked"
    assert record["code"]["source_sha"], "record not sha-stamped"

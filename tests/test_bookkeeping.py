"""BookedVersions gap algebra, ported from the reference's unit tests
(`klukai-types/src/agent.rs:1611-1933` exercises insert_db gap bookkeeping
against an in-memory db; fixtures below mirror its scenarios).
"""

import random

from corrosion_tpu.store.bookkeeping import (
    BookedVersions,
    NULL_GAP_STORE,
    PartialVersion,
    Bookie,
)
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.rangeset import RangeSet

AID = ActorId(b"\x07" * 16)


class RecordingStore:
    """Checks the persisted gap rows always mirror the in-memory set."""

    def __init__(self):
        self.rows = set()

    def delete_gap(self, actor_id, start, end):
        assert (actor_id, start, end) in self.rows, f"missing row {(start, end)}"
        self.rows.discard((actor_id, start, end))

    def insert_gap(self, actor_id, start, end):
        assert (actor_id, start, end) not in self.rows
        self.rows.add((actor_id, start, end))


def observe(bv, store, *ranges):
    snap = bv.snapshot()
    snap.insert_db(store, RangeSet(list(ranges)))
    bv.commit_snapshot(snap)


def test_sequential_no_gaps():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (1, 1))
    observe(bv, store, (2, 5))
    assert bv.max == 5
    assert bv.needed.is_empty()
    assert store.rows == set()
    assert bv.contains_version(3)
    assert not bv.contains_version(6)


def test_gap_created_and_filled():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (1, 2))
    observe(bv, store, (5, 6))  # creates gap 3..4
    assert list(bv.needed) == [(3, 4)]
    assert store.rows == {(AID, 3, 4)}
    assert not bv.contains_version(3)
    assert bv.contains_version(5)
    observe(bv, store, (3, 4))  # fills it
    assert bv.needed.is_empty()
    assert store.rows == set()
    assert bv.contains_all((1, 6))


def test_gap_partially_filled_splits():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (10, 10))  # gap 1..9
    assert list(bv.needed) == [(1, 9)]
    observe(bv, store, (4, 5))
    assert list(bv.needed) == [(1, 3), (6, 9)]
    assert store.rows == {(AID, 1, 3), (AID, 6, 9)}
    observe(bv, store, (1, 3))
    observe(bv, store, (6, 9))
    assert bv.needed.is_empty() and store.rows == set()


def test_out_of_order_first_observation():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (100, 120))
    assert list(bv.needed) == [(1, 99)]
    assert bv.max == 120
    # an already-known version range is a no-op
    observe(bv, store, (100, 120))
    assert list(bv.needed) == [(1, 99)]


def test_multi_range_single_observation():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (5, 6), (10, 12))
    assert list(bv.needed) == [(1, 4), (7, 9)]
    assert bv.max == 12


def test_partials_lifecycle():
    bv = BookedVersions(AID)
    pv = bv.insert_partial(
        3, PartialVersion(seqs=RangeSet([(0, 4)]), last_seq=10, ts=Timestamp(1))
    )
    assert not pv.is_complete()
    assert bv.max == 3  # partial bumps max
    pv = bv.insert_partial(
        3, PartialVersion(seqs=RangeSet([(5, 10)]), last_seq=10, ts=Timestamp(2))
    )
    assert pv.is_complete()
    assert list(pv.gaps()) == []


def test_contains_with_seqs():
    bv = BookedVersions(AID)
    store = RecordingStore()
    observe(bv, store, (1, 5))
    bv.insert_partial(
        5, PartialVersion(seqs=RangeSet([(0, 3)]), last_seq=9, ts=Timestamp(1))
    )
    assert bv.contains(5, (0, 2))
    assert not bv.contains(5, (0, 5))
    assert bv.contains(4, (0, 100))  # no partial → fully applied


def test_randomized_store_mirror():
    rnd = random.Random(7)
    bv = BookedVersions(AID)
    store = RecordingStore()
    for _ in range(300):
        s = rnd.randint(1, 200)
        e = s + rnd.randint(0, 20)
        observe(bv, store, (s, e))
        assert {(st, en) for (_, st, en) in store.rows} == set(bv.needed)
        # invariant: needed never exceeds max, never contains observed
        assert (bv.needed.max() or 0) <= (bv.max or 0)


def test_bookie():
    bookie = Bookie()
    b = bookie.ensure(AID)
    with b.write() as bv:
        bv.insert_partial(
            1, PartialVersion(seqs=RangeSet([(0, 0)]), last_seq=0, ts=Timestamp(1))
        )
    assert bookie.get(AID) is b
    with bookie.ensure(AID).read() as bv:
        assert bv.get_partial(1) is not None

"""runtime/alerts.py: the declarative rule engine (r20).

Fake clocks throughout: the pending→firing→resolved lifecycle, the
Lifeguard-style for-duration widening, the drill mark, the firing side
effects (flight incident + exemplar trace ids), rule parsing, and the
digest wire form of the cluster merge.
"""

from __future__ import annotations

import pytest

from corrosion_tpu.runtime.alerts import (
    DEFAULT_RULES,
    AlertEngine,
    AlertRule,
)
from corrosion_tpu.runtime.config import AlertsConfig
from corrosion_tpu.runtime.digest import (
    NodeDigest,
    decode_digest,
    encode_digest,
)
from corrosion_tpu.runtime.metrics import Registry
from corrosion_tpu.runtime.tsdb import MetricsTSDB


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk_engine(cfg=None, rules=None, reg=None):
    reg = reg or Registry()
    clock = Clock()
    db = MetricsTSDB(
        registry=reg, sample_interval_secs=1.0, clock=clock, wall=clock
    )
    if cfg is None:
        cfg = AlertsConfig()
        if rules is not None:
            cfg.default_pack = False
            cfg.rules = rules
    eng = AlertEngine(
        tsdb=db, cfg=cfg, registry=reg, clock=clock, wall=clock
    )
    return reg, clock, db, eng


RATE_RULE = {
    "name": "faults", "kind": "rate",
    "series": "x.errors.total",
    "op": ">", "value": 0.5, "for_secs": 3.0, "window_secs": 5.0,
    "severity": "page",
}


def drive(reg, clock, db, eng, ticks, inc=0.0):
    """Advance tick-by-tick: optional counter increment, sample, eval."""
    out = []
    c = reg.counter("x.errors.total")
    for _ in range(ticks):
        if inc:
            c.inc(inc)
        db.sample_once()
        out.append(eng.evaluate())
        clock.t += 1.0
    return out


def test_lifecycle_pending_firing_resolved():
    reg, clock, db, eng = mk_engine(rules=[RATE_RULE])
    rounds = drive(reg, clock, db, eng, 2, inc=5.0)
    # condition true but young: pending, not firing
    assert eng.census()["pending"] == ["faults"]
    assert not any(r["fired"] for r in rounds)
    rounds = drive(reg, clock, db, eng, 4, inc=5.0)
    assert any(r["fired"] == ["faults"] for r in rounds)
    assert eng.census()["firing"] == ["faults"]
    # stop the faults: the rate window drains, the alert resolves
    rounds = drive(reg, clock, db, eng, 10, inc=0.0)
    assert any(r["resolved"] == ["faults"] for r in rounds)
    assert eng.census()["firing"] == []
    hist = eng.report()["history"]
    assert [h["event"] for h in hist] == ["fired", "resolved"]
    assert hist[1]["duration_secs"] is not None
    assert reg.counter("corro.alerts.fired.total", rule="faults").value == 1
    assert (
        reg.counter("corro.alerts.resolved.total", rule="faults").value == 1
    )


def test_for_duration_widens_when_node_is_sick():
    """Lifeguard: the same fault pattern fires LATER on a node whose
    own loop is lagging — it distrusts its timers, not its rules."""

    def fire_tick(sick: bool) -> int:
        reg, clock, db, eng = mk_engine(rules=[RATE_RULE])
        if sick:
            # loop lag at 4x the sick threshold -> +1 health point
            reg.gauge("corro.runtime.loop.lag.max.seconds").set(1.0)
        c = reg.counter("x.errors.total")
        for i in range(20):
            c.inc(5.0)
            db.sample_once()
            if eng.evaluate()["fired"]:
                return i
            clock.t += 1.0
        return 99

    healthy, sick = fire_tick(False), fire_tick(True)
    assert healthy < sick < 99  # widened, NOT silenced


def test_widening_caps_at_health_widen_max():
    cfg = AlertsConfig(default_pack=False, rules=[RATE_RULE],
                       health_widen_max=2.0)
    reg, clock, db, eng = mk_engine(cfg=cfg)
    reg.gauge("corro.runtime.loop.lag.max.seconds").set(100.0)
    c = reg.counter("corro.store.write.errors.total", kind="busy")
    db.sample_once()
    c.inc(1000.0)
    clock.t += 1.0
    db.sample_once()
    assert eng.health_score() > 1.0  # both components saturated
    assert eng._widen() == 2.0


def test_firing_attaches_drill_mark_traces_and_incident(tmp_path,
                                                        monkeypatch):
    from corrosion_tpu.chaos.faults import CENSUS
    from corrosion_tpu.runtime import tracestore
    from corrosion_tpu.runtime.records import FLIGHT

    monkeypatch.setenv("CORRO_FLIGHT_DIR", str(tmp_path))
    # the flight recorder needs at least one frame for a dump
    FLIGHT.record_host_frame("test_alerts", {"x": 1})
    st = tracestore.configure(
        targets={}, lottery_n=1, auto_sweep=False
    )
    st.add_span({
        "trace_id": "cafe1234aaaa", "span_id": "1", "parent_span_id": None,
        "name": "write.local", "start_ns": 0, "end_ns": 5_000_000,
        "attrs": {"stage": "write"},
    })
    st.sweep(now=1e9)  # close -> kept by the 1/1 lottery
    reg, clock, db, eng = mk_engine(rules=[RATE_RULE])
    CENSUS.begin("drill-scenario")
    try:
        drive(reg, clock, db, eng, 8, inc=5.0)
    finally:
        CENSUS.end()
        tracestore.configure()
    (active,) = eng.report()["active"]
    assert active["state"] == "firing"
    assert active["drill"] == "drill-scenario"
    assert active["trace_ids"] == ["cafe1234aaaa"]
    assert active["incident"] and "alert_faults" in active["incident"]


def test_threshold_and_absent_kinds():
    rules = [
        {"name": "lag", "kind": "threshold",
         "series": "x.level", "op": ">", "value": 0.5,
         "for_secs": 0.0, "window_secs": 5.0, "agg": "max"},
        {"name": "silent", "kind": "absent",
         "series": "x.level", "for_secs": 0.0, "window_secs": 5.0},
    ]
    reg, clock, db, eng = mk_engine(rules=rules)
    g = reg.gauge("x.level")
    g.set(0.9)
    db.sample_once()
    # for_secs=0: pending and firing collapse into one evaluation
    assert eng.evaluate()["fired"] == ["lag"]
    clock.t += 1.0
    # series vanishes: threshold resolves (no data), absent fires
    clock.t += 50.0
    r = eng.evaluate()
    assert "lag" in r["resolved"]
    assert "silent" in r["fired"]


def test_default_pack_parses_and_operator_override_wins():
    cfg = AlertsConfig(rules=[{
        "name": "loop-lag", "kind": "threshold",
        "series": "corro.runtime.loop.lag.max.seconds",
        "op": ">", "value": 9.0, "for_secs": 1.0, "severity": "page",
    }])
    _reg, _clock, _db, eng = mk_engine(cfg=cfg)
    names = [r.name for r in eng.rules]
    assert len(names) == len(set(names)) == len(DEFAULT_RULES)
    ll = next(r for r in eng.rules if r.name == "loop-lag")
    assert ll.value == 9.0 and ll.severity == "page"


def test_rule_validation_fails_fast():
    with pytest.raises(ValueError):
        AlertRule.from_dict({"name": "x", "kind": "nope", "series": "s"})
    with pytest.raises(ValueError):
        AlertRule.from_dict({"name": "x", "kind": "rate", "series": "s",
                             "op": "~"})
    with pytest.raises(ValueError):
        AlertRule.from_dict({"name": "x", "kind": "rate", "series": "s",
                             "severity": "meh"})
    with pytest.raises(ValueError):
        AlertRule.from_dict({"name": "x", "kind": "rate", "series": "s",
                             "bogus_key": 1})
    # for_scale scales both durations
    r = AlertRule.from_dict(dict(RATE_RULE), for_scale=0.5)
    assert r.for_secs == 1.5 and r.window_secs == 2.5


def test_active_summaries_are_bounded_and_firing_first():
    rules = [
        {"name": f"r{i}", "kind": "threshold", "series": "x.level",
         "op": ">", "value": 0.0, "for_secs": (0.0 if i % 2 else 99.0),
         "window_secs": 5.0}
        for i in range(6)
    ]
    reg, clock, db, eng = mk_engine(rules=rules)
    reg.gauge("x.level").set(1.0)
    db.sample_once()
    eng.evaluate()
    clock.t += 1.0
    eng.evaluate()
    rows = eng.active_summaries(cap=4)
    assert len(rows) == 4
    assert rows[0]["state"] == "firing"
    states = [r["state"] for r in rows]
    assert states == sorted(states, key=lambda s: s != "firing")


def test_alert_summaries_ride_the_digest_wire():
    alerts = [
        {"rule": "store-faults", "severity": "page", "state": "firing",
         "since": 123.25, "value": 7.5, "drill": True},
        {"rule": "loop-lag", "severity": "warn", "state": "pending",
         "since": 124.0, "value": 0.6, "drill": False},
    ]
    d = NodeDigest(
        actor_id=b"\x07" * 16, seq=2, wall=200.0, view_hash=9,
        view_size=3, heads_total=17, alerts=alerts,
    )
    d2 = decode_digest(encode_digest(d))
    assert d2.heads_total == 17
    assert d2.alerts == alerts
    # pre-r20 bytes (no trailing alert block) decode to no alerts —
    # the heads_total eof-tolerance pattern, one field further
    d3 = NodeDigest(
        actor_id=b"\x08" * 16, seq=1, wall=1.0, view_hash=1, view_size=1,
        heads_total=5,
    )
    old_bytes = encode_digest(d3)[:-1]  # strip the alert-count uvarint
    d4 = decode_digest(old_bytes)
    assert d4.heads_total == 5 and d4.alerts == []

"""Subscriptions + updates over HTTP with real agents.

Mirrors the reference's subscription HTTP tests
(`api/public/pubsub.rs:1002,1527`) plus a cross-node flow: subscribe on
one agent, write through another, and observe the change event arrive
via gossip → ingestion → matcher.
"""

import asyncio

from corrosion_tpu.net.mem import MemNetwork

from tests.test_agent import insert, wait_until
from tests.test_http_api import boot_with_api


async def next_of(agen, kind, timeout=10.0):
    """Pull events until one of `kind` arrives."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        remain = deadline - asyncio.get_event_loop().time()
        ev = await asyncio.wait_for(agen.__anext__(), remain)
        if kind in ev:
            return ev


def test_subscription_stream_local():
    async def main():
        net = MemNetwork(seed=31)
        a, api_a, client = await boot_with_api(net, "agent-a")
        try:
            await insert(a, 1, "pre")
            stream = client.subscribe(
                ["SELECT id, text FROM tests WHERE id < ?", [100]]
            )
            it = stream.__aiter__()
            ev = await next_of(it, "columns")
            assert ev == {"columns": ["id", "text"]}
            ev = await next_of(it, "row")
            assert ev["row"] == [1, [1, "pre"]]
            await next_of(it, "eoq")
            assert stream.query_id is not None

            await insert(a, 2, "live")
            ev = await next_of(it, "change")
            kind, _rowid, values, change_id = ev["change"]
            assert (kind, values, change_id) == ("insert", [2, "live"], 1)
            assert stream.last_change_id == 1

            # out-of-predicate write produces no event
            await insert(a, 500, "filtered")
            await insert(a, 3, "three")
            ev = await next_of(it, "change")
            assert ev["change"][2] == [3, "three"]
        finally:
            await client.close()
            await api_a.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_subscription_catch_up_and_reattach():
    async def main():
        net = MemNetwork(seed=32)
        a, api_a, client = await boot_with_api(net, "agent-a")
        try:
            s1 = client.subscribe("SELECT text FROM tests", skip_rows=True)
            it1 = s1.__aiter__()
            await next_of(it1, "eoq")
            qid = s1.query_id

            await insert(a, 1, "one")
            await next_of(it1, "change")

            # second subscriber re-attaches by id from change id 0:
            # replays the full log
            s2 = client.subscribe("SELECT text FROM tests", from_change=0)
            s2.query_id = qid
            it2 = s2.__aiter__()
            ev = await next_of(it2, "change")
            assert ev["change"][0] == "insert" and ev["change"][2] == ["one"]

            # live event flows to both
            await insert(a, 2, "two")
            e1 = await next_of(it1, "change")
            e2 = await next_of(it2, "change")
            assert e1 == e2
            assert e1["change"][3] == 2
        finally:
            await client.close()
            await api_a.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_subscription_cross_node_via_gossip():
    async def main():
        net = MemNetwork(seed=33)
        a, api_a, client_a = await boot_with_api(net, "agent-a")
        b, api_b, client_b = await boot_with_api(net, "agent-b", ["agent-a"])
        try:
            await wait_until(lambda: len(a.members) == 1 and len(b.members) == 1)

            stream = client_b.subscribe("SELECT id, text FROM tests")
            it = stream.__aiter__()
            await next_of(it, "eoq")

            # write on A; matcher event must surface on B through gossip
            await insert(a, 7, "crossed")
            ev = await next_of(it, "change", timeout=15.0)
            assert ev["change"][0] == "insert"
            assert ev["change"][2] == [7, "crossed"]
        finally:
            await client_a.close()
            await client_b.close()
            await api_a.stop()
            await api_b.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)
            await shutdown(b)

    asyncio.run(main())


def test_updates_stream_http():
    async def main():
        net = MemNetwork(seed=34)
        a, api_a, client = await boot_with_api(net, "agent-a")
        try:
            agen = client.updates("tests")
            # prime the stream: handler registers before the first event
            task = asyncio.ensure_future(agen.__anext__())
            await asyncio.sleep(0.2)
            await insert(a, 9, "x")
            ev = await asyncio.wait_for(task, 10)
            assert ev == {"notify": ["insert", [9]]}

            await insert(a, 9, "y")
            ev = await asyncio.wait_for(agen.__anext__(), 10)
            assert ev == {"notify": ["update", [9]]}
        finally:
            await client.close()
            await api_a.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_subscription_exactly_once_under_concurrent_writers():
    """Consistency contract under write pressure: a subscriber on node C
    observes EVERY row written concurrently on nodes A and B exactly
    once, with strictly increasing ChangeIds and no gaps (the guarantee
    behind the client's reconnect-from-ChangeId resume,
    `client/src/sub.rs`; events come from the EXCEPT-style diff so
    duplicate gossip deliveries must not produce duplicate events)."""

    async def main():
        net = MemNetwork(seed=35)
        a, api_a, client_a = await boot_with_api(net, "agent-a")
        b, api_b, client_b = await boot_with_api(net, "agent-b", ["agent-a"])
        c, api_c, client_c = await boot_with_api(net, "agent-c", ["agent-a"])
        agents = (a, b, c)
        try:
            assert await wait_until(
                lambda: all(len(ag.members) == 2 for ag in agents)
            ), "cluster never converged"
            stream = client_c.subscribe("SELECT id, text FROM tests")
            it = stream.__aiter__()
            await next_of(it, "eoq")

            rows_per_writer = 10

            async def writer(base, ag):
                for r in range(rows_per_writer):
                    await insert(ag, base + r, f"w{base}-{r}")

            await asyncio.gather(writer(0, a), writer(1000, b))

            seen = {}
            change_ids = []
            for _ in range(2 * rows_per_writer):
                ev = await next_of(it, "change", timeout=30.0)
                kind, _rowid, values, change_id = ev["change"]
                assert kind == "insert", ev
                rid = values[0]
                assert rid not in seen, f"duplicate event for row {rid}"
                seen[rid] = values[1]
                change_ids.append(change_id)

            expected = {r for r in range(rows_per_writer)} | {
                1000 + r for r in range(rows_per_writer)
            }
            assert set(seen) == expected
            # strictly increasing, gap-free ChangeId log
            assert change_ids == list(
                range(change_ids[0], change_ids[0] + len(change_ids))
            ), change_ids

            # the stream must now be QUIET: a late duplicate from a
            # re-gossiped delivery would arrive before this sentinel
            await insert(a, 9999, "sentinel")
            ev = await next_of(it, "change", timeout=15.0)
            assert ev["change"][2] == [9999, "sentinel"], (
                f"late duplicate event before the sentinel: {ev}"
            )
        finally:
            for cl in (client_a, client_b, client_c):
                await cl.close()
            for api in (api_a, api_b, api_c):
                await api.stop()
            from corrosion_tpu.agent.run import shutdown

            for ag in agents:
                await shutdown(ag)

    asyncio.run(main())


def test_matcher_death_surfaces_typed_error_frame():
    """r10 regression: a matcher whose diff loop dies mid-stream must
    end every attached subscription with an {"error": ...} frame that
    carries the failure — not a hang, and not an AttributeError from a
    bare None sentinel.  Catch-up by id from the dead sub must 404."""

    async def main():
        net = MemNetwork(seed=42)
        a, api, client = await boot_with_api(net, "agent-dead")
        try:
            stream = client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True
            )
            it = stream.__aiter__()
            await next_of(it, "eoq")
            qid = stream.query_id

            # live event proves the stream works, then kill the matcher
            await insert(a, 1, "alive")
            await next_of(it, "change")

            handle = api.subs.get(qid)

            def boom(_cands):
                raise RuntimeError("diff exploded (injected)")

            handle.matcher.handle_candidates = boom
            await insert(a, 2, "doomed")

            ev = await asyncio.wait_for(it.__anext__(), 15)
            while "error" not in ev:
                ev = await asyncio.wait_for(it.__anext__(), 15)
            assert "diff exploded" in ev["error"], ev
            assert handle.error is not None

            # stream ended cleanly after the error frame
            with __import__("pytest").raises(StopAsyncIteration):
                await asyncio.wait_for(it.__anext__(), 15)

            # catch-up on the dead sub is refused, not hung
            s2 = client.subscribe(
                "SELECT id, text FROM tests", from_change=0
            )
            s2.query_id = qid
            it2 = s2.__aiter__()
            got_err = None
            try:
                async for ev in it2:
                    if "error" in ev:
                        got_err = ev
                        break
            except Exception as e:  # 404 surfaces as ClientError
                got_err = {"error": str(e)}
            assert got_err is not None
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())


def test_subscription_rows_across_sign_boundary():
    """Regression: integer pks 128..255 pack into a sign-ambiguous byte
    upstream (encoder/decoder asymmetry, pubsub.rs:2315-2340 vs get_int)
    and the matcher's temp-table diff silently dropped their events —
    a subscription stalled at exactly id 127. The widened encoder
    (types/pack.py) must deliver every row."""

    async def main():
        net = MemNetwork(seed=41)
        a, api, client = await boot_with_api(net, "agent-sb")
        try:
            got = []

            async def subscriber():
                async for ev in client.subscribe(
                    "SELECT id, text FROM tests", skip_rows=True
                ):
                    if "change" in ev:
                        got.append(ev["change"][2][0])
                        if len(got) >= 40:
                            return

            task = asyncio.ensure_future(subscriber())
            await asyncio.sleep(0.3)
            stmts = [
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [i, f"v{i}"]]
                for i in range(110, 150)  # crosses the 128 boundary
            ]
            await client.execute(stmts)
            await asyncio.wait_for(task, 30)
            assert sorted(got) == list(range(110, 150))
        finally:
            await client.close()
            await api.stop()
            from corrosion_tpu.agent.run import shutdown

            await shutdown(a)

    asyncio.run(main())

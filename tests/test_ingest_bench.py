"""Banked-record guard for INGEST_BENCH.json (r14 write-path round).

`scripts/bench_ingest.py --ab` banks the pre/post trajectory of the
local-commit plane (group commit + vectorized finalize + encode-once)
in one sha-stamped artifact.  This guard pins the artifact's shape and
the round's headline margins so a silent regression — or a hand-edited
number — fails tier-1 (test_bench_replay.py discipline: a banked
number must be tied to real code and hold its acceptance floor).
"""

from __future__ import annotations

import json
import os

import pytest

PATH = os.path.join(os.path.dirname(__file__), "..", "INGEST_BENCH.json")

LOCAL_RUNGS = [
    f"ingest-local-w{n}{d}"
    for n in (1, 4, 16)
    for d in ("", "-durable")
]
ALL_RUNGS = LOCAL_RUNGS + ["ingest-remote", "ingest-conflict", "ingest-e2e"]


@pytest.fixture(scope="module")
def banked() -> dict:
    with open(PATH) as f:
        return {r["rung"]: r for r in json.load(f)}


def test_all_rungs_banked_pre_and_post(banked):
    for rung in ALL_RUNGS:
        for mode in ("pre", "post"):
            assert f"{rung}-{mode}" in banked, f"missing {rung}-{mode}"


def test_records_are_sha_stamped(banked):
    for rung, rec in banked.items():
        sha = rec.get("code_sha")
        assert sha, f"{rung}: no code fingerprint"
        assert all(v != "missing" for v in sha.values()), (rung, sha)
        assert rec.get("measured_at"), f"{rung}: no measured_at"


def test_sixteen_writer_rung_speedup_floor(banked):
    """The headline coalescing margin: at 16 concurrent writers the
    post write path must hold ≥1.5× banked rows/s (measured 1.7×
    default / 2.1× durable on the 1-core bench host; the pre-r14 path
    is flat across writer counts because every writer paid a full
    serialized commit)."""
    for suffix in ("", "-durable"):
        pre = banked[f"ingest-local-w16{suffix}-pre"]["rows_per_s"]
        post = banked[f"ingest-local-w16{suffix}-post"]["rows_per_s"]
        assert post / pre >= 1.5, (suffix, pre, post)


def test_sixteen_writer_commit_latency_halves(banked):
    """Group commit's per-writer view: a 16-writer burst's p50 commit
    latency drops (writers no longer queue behind 15 full commits)."""
    for suffix in ("", "-durable"):
        pre = banked[f"ingest-local-w16{suffix}-pre"]["commit_p50_ms"]
        post = banked[f"ingest-local-w16{suffix}-post"]["commit_p50_ms"]
        assert post <= pre * 0.75, (suffix, pre, post)


def test_solo_writer_p50_unchanged(banked):
    """The solo fast path: a lone writer's p50 commit latency must not
    regress (first writer commits immediately when nobody is queued)."""
    for suffix in ("", "-durable"):
        pre = banked[f"ingest-local-w1{suffix}-pre"]["commit_p50_ms"]
        post = banked[f"ingest-local-w1{suffix}-post"]["commit_p50_ms"]
        assert post <= pre * 1.25, (suffix, pre, post)


def test_write_event_p50_collapses(banked):
    """The e2e satellite: candidate_batch_wait 0.6→0.1 s + encode-once
    drop the write→event total p50 by ≥3× (banked 0.61 s → 0.11 s)."""
    pre = banked["ingest-e2e-pre"]["total_p50_s"]
    post = banked["ingest-e2e-post"]["total_p50_s"]
    assert post <= pre / 3, (pre, post)
    # and every banked e2e write produced its event (no missed deliveries)
    for mode in ("pre", "post"):
        rec = banked[f"ingest-e2e-{mode}"]
        assert rec["events"] >= rec["writes"]


def test_remote_apply_not_regressed(banked):
    """The r2 batched remote-apply plane rode along untouched."""
    for rung in ("ingest-remote", "ingest-conflict"):
        pre = banked[f"{rung}-pre"]["rows_per_s"]
        post = banked[f"{rung}-post"]["rows_per_s"]
        assert post >= pre * 0.85, (rung, pre, post)


# -- r15: direct change capture A/B (tagged rungs, r14 records kept) --------
#
# The r15 `--ab --tag r15` axis isolates the CAPTURE ENGINE
# (CORRO_CAPTURE=trigger vs direct) with group commit / vectorized
# finalize / encode-once identical on both sides.  The bench host is a
# contended 1-core VM whose throughput swings individual rungs ±30%
# between back-to-back runs (pre/post run ADJACENT per rung to kill
# drift bias), so these guards pin aggregates and absolutes; the
# DETERMINISTIC capture win — zero `__crdt_pending` statements on a
# fully-captured transaction, byte-identical change streams — is
# pinned in tests/test_capture.py where noise cannot reach it.


def test_r15_capture_ab_banked_and_stamped(banked):
    for rung in ALL_RUNGS:
        for mode in ("pre", "post"):
            key = f"{rung}-{mode}-r15"
            assert key in banked, f"missing {key}"
            sha = banked[key].get("code_sha", {})
            assert "corrosion_tpu/store/capture.py" in sha, key
            assert all(v != "missing" for v in sha.values()), (key, sha)


def test_r15_direct_capture_throughput_parity(banked):
    """Direct capture must not cost local-write throughput: banked
    aggregate across the six local rungs stays within host noise of
    the trigger engine."""
    pre = sum(banked[f"{r}-pre-r15"]["rows_per_s"] for r in LOCAL_RUNGS)
    post = sum(banked[f"{r}-post-r15"]["rows_per_s"] for r in LOCAL_RUNGS)
    assert post >= 0.70 * pre, (pre, post)


def test_r15_solo_commit_latency_bounded(banked):
    """The uncontended writer's p50 commit stays in the ~1 ms band the
    r14 round established (0.89 ms on a quiet host; the banked bound
    absorbs the bench VM's measured jitter)."""
    for suffix in ("", "-durable"):
        rec = banked[f"ingest-local-w1{suffix}-post-r15"]
        assert rec["commit_p50_ms"] <= 2.5, rec


def test_r15_e2e_write_event_p50_held(banked):
    """The live write→event path holds the r14 ~0.1 s p50 under direct
    capture, with every write delivered."""
    rec = banked["ingest-e2e-post-r15"]
    assert rec["total_p50_s"] <= 0.3, rec
    assert rec["events"] >= rec["writes"]


# -- r21: columnar finalize + per-group amortization (tagged rungs) ----------
#
# The r21 `--ab --tag r21` axis isolates the WRITE-PATH ROUND-3 delta
# (pre = CORRO_FINALIZE=vector + CORRO_GROUP_FANOUT=0, the shipped r15
# behavior; post = columnar finalize + amortized group fanout +
# full-occupancy gathering) with direct capture / group commit /
# encode-once identical on both sides.  r21 records are the MEDIAN of
# `AB_REPS` interleaved repetitions per mode (`run_ab`), so the
# headline ratio guard can sit near the measured margin instead of
# absorbing the single-run ±30% jitter the r15 guards had to.  The
# deterministic half of the round — byte/clock-identical changes
# across finalize engines, per-group statement profile — is pinned in
# tests/test_finalize_batch.py where host noise cannot reach it.

R21_SHA_FILES = (
    "corrosion_tpu/store/crdt.py",
    "corrosion_tpu/agent/run.py",
    "corrosion_tpu/agent/handle.py",
    "corrosion_tpu/agent/broadcast.py",
    "corrosion_tpu/runtime/channels.py",
    "corrosion_tpu/types/codec.py",
)


def test_r21_ab_banked_and_stamped(banked):
    for rung in ALL_RUNGS:
        for mode in ("pre", "post"):
            key = f"{rung}-{mode}-r21"
            assert key in banked, f"missing {key}"
            sha = banked[key].get("code_sha", {})
            for path in R21_SHA_FILES:
                assert path in sha, (key, path)
            assert all(v != "missing" for v in sha.values()), (key, sha)


def test_r21_sixteen_writer_speedup_floor(banked):
    """The round's headline: at 16 concurrent writers the columnar +
    amortized path holds ≥1.25× banked rows/s (measured 1.37×: batch
    occupancy 8.1 → 15.6 of 16 from the gather yield, one fanout pass
    per batch instead of 16, columnar finalize under the lock)."""
    pre = banked["ingest-local-w16-pre-r21"]["rows_per_s"]
    post = banked["ingest-local-w16-post-r21"]["rows_per_s"]
    assert post / pre >= 1.25, (pre, post)


def test_r21_sixteen_writer_latency_drops(banked):
    """Full batches halve the number of commit rounds a writer waits
    behind: banked w16 p50 drops ≥15% (measured 27.2 → 19.7 ms) and
    p99 must not regress."""
    pre = banked["ingest-local-w16-pre-r21"]
    post = banked["ingest-local-w16-post-r21"]
    assert post["commit_p50_ms"] <= pre["commit_p50_ms"] * 0.85, (pre, post)
    assert post["commit_p99_ms"] <= pre["commit_p99_ms"], (pre, post)


def test_r21_local_aggregate_not_regressed(banked):
    """No rung pays for the w16 win: banked aggregate across the six
    local rungs stays at least at parity (measured 1.14×)."""
    pre = sum(banked[f"{r}-pre-r21"]["rows_per_s"] for r in LOCAL_RUNGS)
    post = sum(banked[f"{r}-post-r21"]["rows_per_s"] for r in LOCAL_RUNGS)
    assert post >= 0.90 * pre, (pre, post)


def test_r21_solo_p50_parity(banked):
    """The uncontended writer pays one ready-queue pass, not a timed
    wait: solo p50 stays within 25% of the r15 path on the same host
    minute (measured 1.03× / 0.99× durable)."""
    for suffix in ("", "-durable"):
        pre = banked[f"ingest-local-w1{suffix}-pre-r21"]["commit_p50_ms"]
        post = banked[f"ingest-local-w1{suffix}-post-r21"]["commit_p50_ms"]
        assert post <= pre * 1.25, (suffix, pre, post)


def test_r21_apply_rungs_untouched(banked):
    """The remote-apply plane is outside the round's blast radius; the
    loose bound is the 0.16 s conflict rung's residual jitter, not an
    accepted cost."""
    for rung in ("ingest-remote", "ingest-conflict"):
        pre = banked[f"{rung}-pre-r21"]["rows_per_s"]
        post = banked[f"{rung}-post-r21"]["rows_per_s"]
        assert post >= pre * 0.70, (rung, pre, post)


def test_r21_e2e_write_event_p50_held(banked):
    """write→event p50 holds the ~0.1 s band under the amortized
    fanout, with every write delivered."""
    rec = banked["ingest-e2e-post-r21"]
    assert rec["total_p50_s"] <= 0.3, rec
    assert rec["events"] >= rec["writes"]


# -- r24: dedicated committer thread + native finalize (tagged rungs) --------
#
# The r24 `--ab --tag r24` axis isolates the WRITE-PATH ROUND-4 delta
# (pre = CORRO_COMMITTER=to_thread + CORRO_FINALIZE=columnar, the
# shipped r21–r23 behavior; post = dedicated committer thread + native
# C++ phase B) with capture / group commit / fanout identical on both
# sides.  Same interleaved-median protocol as r21.  This round's target
# is the SOLO writer's plumbing floor — the per-batch to_thread hop and
# the Python decision loop — so the headline guard is w1 p50; w16 was
# already amortization-bound and must simply hold.  The deterministic
# half — bit-identical changes across all four engines, the counted
# no-compiler fallback, the cross-language ABI pins — lives in
# tests/test_finalize_batch.py and the `finalize-parity` lint rule
# where host noise cannot reach it.

R24_SHA_FILES = R21_SHA_FILES + (
    "corrosion_tpu/native.py",
    "native/crdt_batch.cpp",
)


def test_r24_ab_banked_and_stamped(banked):
    for rung in ALL_RUNGS:
        for mode in ("pre", "post"):
            key = f"{rung}-{mode}-r24"
            assert key in banked, f"missing {key}"
            sha = banked[key].get("code_sha", {})
            for path in R24_SHA_FILES:
                assert path in sha, (key, path)
            assert all(v != "missing" for v in sha.values()), (key, sha)


def test_r24_solo_p50_improves(banked):
    """The round's headline: the uncontended writer's p50 commit drops
    ≥10% once the leader hands its batch to the long-lived committer
    thread instead of the executor (measured 1.75 → 1.36 ms, with the
    native decision loop shaving the finalize on top).  The durable
    rung is fsync-bound and only held to parity below."""
    pre = banked["ingest-local-w1-pre-r24"]["commit_p50_ms"]
    post = banked["ingest-local-w1-post-r24"]["commit_p50_ms"]
    assert post <= pre * 0.90, (pre, post)
    # and the absolute band the r14/r15 rounds established still holds
    assert post <= 2.5, post


def test_r24_solo_throughput_floor(banked):
    """w1 rows/s must show the plumbing win, not just the latency
    quantile (measured 1.20×; the floor absorbs re-bank drift)."""
    pre = banked["ingest-local-w1-pre-r24"]["rows_per_s"]
    post = banked["ingest-local-w1-post-r24"]["rows_per_s"]
    assert post >= pre * 1.05, (pre, post)


def test_r24_sixteen_writer_holds(banked):
    """No w16 regression: the contended plane was already
    amortization-bound (one handoff per BATCH, so the hop the round
    removed was 1/16th as hot) — banked rows/s holds parity (measured
    1.01×, durable 0.91× inside the host noise band)."""
    pre = banked["ingest-local-w16-pre-r24"]["rows_per_s"]
    post = banked["ingest-local-w16-post-r24"]["rows_per_s"]
    assert post >= pre * 0.95, (pre, post)
    pre_d = banked["ingest-local-w16-durable-pre-r24"]["rows_per_s"]
    post_d = banked["ingest-local-w16-durable-post-r24"]["rows_per_s"]
    assert post_d >= pre_d * 0.85, (pre_d, post_d)


def test_r24_local_aggregate_not_regressed(banked):
    """No rung pays for the solo win: banked aggregate across the six
    local rungs stays at least at parity (measured 1.03×)."""
    pre = sum(banked[f"{r}-pre-r24"]["rows_per_s"] for r in LOCAL_RUNGS)
    post = sum(banked[f"{r}-post-r24"]["rows_per_s"] for r in LOCAL_RUNGS)
    assert post >= 0.90 * pre, (pre, post)


def test_r24_apply_rungs_untouched(banked):
    """The remote-apply plane is outside the round's blast radius
    (measured 0.96× / 0.89×; the 0.70 floor is the conflict rung's
    residual jitter, r21 precedent)."""
    for rung in ("ingest-remote", "ingest-conflict"):
        pre = banked[f"{rung}-pre-r24"]["rows_per_s"]
        post = banked[f"{rung}-post-r24"]["rows_per_s"]
        assert post >= pre * 0.70, (rung, pre, post)


def test_r24_e2e_write_event_p50_held(banked):
    """write→event p50 holds the ~0.1 s band with the committer thread
    in the loop, every write delivered."""
    rec = banked["ingest-e2e-post-r24"]
    assert rec["total_p50_s"] <= 0.3, rec
    assert rec["events"] >= rec["writes"]

"""runtime/tsdb.py: the bounded ring-buffer metrics TSDB (r20).

Everything runs on fake clocks — the sampler's arithmetic (counter
rates, gauge levels, latency quantile fields), the ring/series bounds
with their typed accounting, and the query aggregations the alert
engine evaluates with.
"""

from __future__ import annotations

from corrosion_tpu.runtime import tsdb as tsdb_mod
from corrosion_tpu.runtime.metrics import Registry
from corrosion_tpu.runtime.tsdb import MetricsTSDB


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk(reg=None, **kw):
    reg = reg or Registry()
    clock = Clock()
    kw.setdefault("sample_interval_secs", 1.0)
    db = MetricsTSDB(registry=reg, clock=clock, wall=clock, **kw)
    return reg, clock, db


def test_counter_becomes_windowed_rate():
    reg, clock, db = mk()
    c = reg.counter("x.total")
    db.sample_once()  # first sight: cumulative recorded, no rate point
    assert db.window("x.total:rate", window_secs=60) == []
    c.inc(10)
    clock.t += 2.0
    db.sample_once()
    pts = db.window("x.total:rate", window_secs=60)
    assert len(pts) == 1 and pts[0][1] == 5.0  # 10 over 2 s
    # a counter RESET (restart) clamps at 0 instead of a negative rate
    with c._lock:
        c.value = 0.0
    clock.t += 1.0
    db.sample_once()
    assert db.window("x.total:rate", window_secs=60)[-1][1] == 0.0


def test_gauge_and_latency_fields():
    reg, clock, db = mk()
    reg.gauge("x.level").set(7.5)
    w = reg.latency("x.seconds")
    for v in (0.010, 0.020, 0.100):
        w.observe(v)
    db.sample_once()
    assert db.aggregate("x.level", window_secs=10) == 7.5
    p50 = db.aggregate("x.seconds:p50", window_secs=10)
    p99 = db.aggregate("x.seconds:p99", window_secs=10)
    assert p50 is not None and p99 is not None and p99 >= p50
    # histogram/latency counts surface as rates on the next tick
    clock.t += 1.0
    w.observe(0.050)
    db.sample_once()
    assert db.aggregate("x.seconds:rate", window_secs=10) == 1.0


def test_ring_depth_bounds_points_per_series():
    reg, clock, db = mk(slots=5)
    g = reg.gauge("x.level")
    for i in range(12):
        g.set(float(i))
        db.sample_once()
        clock.t += 1.0
    pts = db.window("x.level", window_secs=1000)
    assert len(pts) == 5  # ring depth, not sample count
    assert [v for _w, v in pts] == [7.0, 8.0, 9.0, 10.0, 11.0]


def test_max_series_cap_drops_typed():
    reg, clock, db = mk(max_series=10)
    for i in range(30):
        reg.gauge("g.level", idx=str(i)).set(1.0)
    db.sample_once()
    assert db.census()["series"] == 10
    assert reg.counter("corro.tsdb.series.dropped.total").value > 0


def test_memory_accounting_gauges():
    reg, clock, db = mk()
    reg.gauge("x.level").set(1.0)
    db.sample_once()
    snap = {
        name: v for _k, name, _l, v in reg.snapshot()
        if name.startswith("corro.tsdb.")
    }
    assert snap["corro.tsdb.series"] == db.census()["series"] > 0
    assert snap["corro.tsdb.points"] == db.census()["points"] > 0
    assert snap["corro.tsdb.bytes.est"] > 0
    assert snap["corro.tsdb.samples.total"] == 1


def test_aggregate_across_label_sets_and_over_time():
    reg, clock, db = mk()
    a = reg.counter("x.total", kind="a")
    b = reg.counter("x.total", kind="b")
    db.sample_once()
    for inc_a, inc_b in ((4, 2), (8, 2)):
        a.inc(inc_a)
        b.inc(inc_b)
        clock.t += 1.0
        db.sample_once()
    # sum across label sets, avg over ticks: (6 + 10) / 2
    assert db.aggregate(
        "x.total:rate", window_secs=60, across="sum", over="avg"
    ) == 8.0
    # label filter narrows to one set
    assert db.aggregate(
        "x.total:rate", labels={"kind": "b"}, window_secs=60,
        across="sum", over="avg",
    ) == 2.0
    assert db.aggregate(
        "x.total:rate", window_secs=60, across="max", over="max"
    ) == 8.0
    # no matching points in the window -> None (the alert engine's
    # "no data, no verdict" rule)
    clock.t += 1000.0
    assert db.aggregate("x.total:rate", window_secs=10) is None


def test_absent_fires_only_for_vanished_series():
    reg, clock, db = mk()
    # never-seen series: NOT absent (a plane that never started must
    # not page)
    assert not db.absent("ghost.level", window_secs=10)
    reg.gauge("x.level").set(1.0)
    db.sample_once()
    assert not db.absent("x.level", window_secs=10)
    clock.t += 100.0
    assert db.absent("x.level", window_secs=10)


def test_global_install_mirrors_tracestore():
    try:
        db = tsdb_mod.configure(
            auto_sample=False, sample_interval_secs=1.0,
            registry=Registry(),
        )
        assert tsdb_mod.get() is db
        assert tsdb_mod.ensure(sample_interval_secs=9.0) is db  # first wins
        assert db.sample_interval_secs == 1.0
    finally:
        tsdb_mod.configure()
    assert tsdb_mod.get() is None

"""Continuous profiling plane (r23): sampler, fold store, statement
shapes, adaptive shed, export formats.

The sampler is driven SYNCHRONOUSLY here — `Profiler.sample_once()` is
the documented test mode (the daemon thread is just a loop around it),
so every assertion below is deterministic: no sleeping for a sampler
tick, no racing the adaptive governor.  The deliberately hot function
keeps a call-free loop body so every sample charges the SAME leaf
frame — flamegraph dominance becomes an exact count, not a likelihood.
"""

import threading
import time

from corrosion_tpu.runtime import profiler as prof_mod
from corrosion_tpu.runtime.metrics import Registry
from corrosion_tpu.runtime.profiler import ADAPT_EVERY, Profiler
from corrosion_tpu.runtime.profstore import (
    OVERFLOW_KEY,
    ProfStore,
    self_times,
    to_folded_text,
)
from corrosion_tpu.runtime.trace import timed_query


def _deliberately_hot_spin(ready, flag):
    # call-free loop body: every stack sample of this thread lands with
    # THIS frame as the leaf (a stop Event's is_set() call would split
    # the self time with threading.py)
    ready.set()
    x = 0
    while not flag:
        x = (x + 1) % 1000003
    return x


def _drive(p, n):
    for _ in range(n):
        p.sample_once()


# -- hot-frame dominance ----------------------------------------------------


def test_hot_function_dominates_folded_output():
    p = Profiler(hz=1000.0, window_secs=600.0, registry=Registry())
    ready, flag = threading.Event(), []
    t = threading.Thread(
        target=_deliberately_hot_spin,
        args=(ready, flag),
        name="asyncio_hotspin",  # _NAME_TAGS: asyncio_ -> worker
        daemon=True,
    )
    t.start()
    assert ready.wait(5.0)
    n = 150
    try:
        _drive(p, n)
    finally:
        flag.append(1)
        t.join(timeout=5.0)

    folded = p.folded()
    hot = {k: v for k, v in folded.items() if "_deliberately_hot_spin" in k}
    # the spin thread was inside the hot frame for (almost) every tick;
    # the sample landing exactly on ready.set() gets the 10% slack
    assert sum(hot.values()) >= 0.9 * n, folded
    # classified by thread-name prefix, no running asyncio task
    assert all(k.startswith("worker;-;") for k in hot), hot
    # and the hot frame is the LEAF of its stacks — top SELF time, not
    # just presence (a call inside the loop body would split the count
    # with the callee).  Dominance is asserted WITHIN the spin thread's
    # own stacks: a wall-clock sampler also charges every other thread
    # alive in the pytest process (pool threads parked by earlier test
    # files merge into identical folded keys whose count scales with
    # pool size), so a process-wide self-time ranking is inherently
    # order-dependent.
    rows = self_times(hot)
    assert rows and "_deliberately_hot_spin" in rows[0][0], rows[:5]
    assert rows[0][1] >= 0.9 * n

    text = to_folded_text(folded)
    for line in text.strip().splitlines():
        stack, _, cnt = line.rpartition(" ")
        assert stack.count(";") >= 1 and int(cnt) > 0


# -- adaptive shed ----------------------------------------------------------


def test_adaptive_shed_engages_and_restores():
    reg = Registry()
    p = Profiler(
        hz=50.0, shed_hz=5.0, max_overhead_pct=1e-9, registry=reg
    )
    # a tight synchronous block busts ANY positive budget
    _drive(p, ADAPT_EVERY)
    assert p.shed is True
    assert p.sheds_total == 1
    assert p._interval == 1.0 / p.shed_hz
    assert reg.counter("corro.profile.shed.total").value == 1
    assert p.overhead_pct > 0.0

    # with the budget effectively unbounded the projected full-rate
    # duty clears the half-budget hysteresis bar -> restore
    p.max_overhead_pct = 1e9
    _drive(p, ADAPT_EVERY)
    assert p.shed is False
    assert p._interval == 1.0 / p.hz
    # shed counter is monotone: restore does not decrement
    assert reg.counter("corro.profile.shed.total").value == 1
    assert p.census()["sheds_total"] == 1


# -- ring bounds ------------------------------------------------------------


def test_fold_map_overflow_is_typed_not_silent():
    st = ProfStore(window_secs=600.0, max_stacks=4)
    for i in range(10):
        st.add_sample("loop;-;app.py:f%d" % i)
    folded = st.merged()
    assert len(folded) == 5  # 4 distinct + the overflow bucket
    assert folded[OVERFLOW_KEY] == 6
    assert sum(folded.values()) == 10  # accounted, never dropped


def test_window_ring_is_bounded_and_lookback_filters():
    clock = [1000.0]
    st = ProfStore(window_secs=5.0, slots=3, wall=lambda: clock[0])
    for i in range(10):
        st.add_sample("w;-;app.py:f%d" % i)
        clock[0] += 6.0
        st.seal_coldpath()
    c = st.census()
    assert c["windows_sealed"] == 3  # deque bound
    assert st.sealed_total == 10
    assert set(st.merged()) == {
        "w;-;app.py:f7", "w;-;app.py:f8", "w;-;app.py:f9"
    }
    # lookback 7s from t=1060 keeps windows sealed at 1054 and 1060
    assert set(st.merged(7.0)) == {"w;-;app.py:f8", "w;-;app.py:f9"}


# -- speedscope export ------------------------------------------------------

# the essential subset of speedscope's file-format-schema.json: enough
# to reject a malformed document (missing frame table, non-sampled
# profile, weights/samples shape drift) without vendoring the full
# schema
_SPEEDSCOPE_SCHEMA = {
    "type": "object",
    "required": ["$schema", "shared", "profiles"],
    "properties": {
        "$schema": {"type": "string"},
        "shared": {
            "type": "object",
            "required": ["frames"],
            "properties": {
                "frames": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["name"],
                        "properties": {"name": {"type": "string"}},
                    },
                }
            },
        },
        "profiles": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": [
                    "type", "name", "unit", "startValue", "endValue",
                    "samples", "weights",
                ],
                "properties": {
                    "type": {"enum": ["sampled"]},
                    "unit": {"type": "string"},
                    "startValue": {"type": "number"},
                    "endValue": {"type": "number"},
                    "samples": {
                        "type": "array",
                        "items": {
                            "type": "array",
                            "items": {"type": "integer", "minimum": 0},
                        },
                    },
                    "weights": {
                        "type": "array",
                        "items": {"type": "number", "minimum": 0},
                    },
                },
            },
        },
    },
}


def test_speedscope_export_validates_against_schema():
    import jsonschema

    p = Profiler(window_secs=600.0, registry=Registry())
    for _ in range(5):
        p.ring.add_sample("loop;tick;app.py:main;app.py:step")
    for _ in range(3):
        p.ring.add_sample("store;-;crdt.py:commit")
    doc = p.export(fmt="speedscope")
    jsonschema.validate(doc, _SPEEDSCOPE_SCHEMA)

    prof = doc["profiles"][0]
    nframes = len(doc["shared"]["frames"])
    # loop/tick/main/step + store/-/commit: no frame shared between them
    assert nframes == 7
    assert len(prof["samples"]) == len(prof["weights"]) == 2
    assert all(i < nframes for s in prof["samples"] for i in s)
    assert prof["endValue"] == sum(prof["weights"]) == 8


# -- statement shapes match the trace-callback counts -----------------------


def test_stmt_histograms_match_trace_callback_counts():
    reg = Registry()
    prof_mod.configure(auto_start=False, registry=reg, window_secs=600.0)
    try:
        for _ in range(7):
            with timed_query("SELECT 1", shape="test:select"):
                pass
        for _ in range(3):
            with timed_query("INSERT INTO t", shape="test:insert"):
                pass
        with timed_query("no shape given"):
            pass  # shapeless blocks stay out of the profile

        h = reg.histogram("corro.store.stmt.seconds", shape="test:select")
        assert h.count == 7
        h2 = reg.histogram("corro.store.stmt.seconds", shape="test:insert")
        assert h2.count == 3

        rows = {r["shape"]: r for r in prof_mod.get().ring.stmt_rows()}
        assert rows["test:select"]["count"] == 7
        assert rows["test:insert"]["count"] == 3
        assert set(rows) == {"test:select", "test:insert"}

        cap = prof_mod.get().capture("alert_test")
        assert cap["reason"] == "alert_test"
        assert {r["shape"] for r in cap["stmt"]} == {
            "test:select", "test:insert"
        }
    finally:
        prof_mod.configure()  # uninstall; later tests see a clean plane
    assert prof_mod.installed() is False
    # uninstalled, the trace hook is a no-op (one module-global read)
    with timed_query("SELECT 1", shape="test:select"):
        pass
    assert reg.histogram(
        "corro.store.stmt.seconds", shape="test:select"
    ).count == 7


# -- record_write_buckets ---------------------------------------------------


def test_write_buckets_partition_the_wall():
    reg = Registry()
    prof_mod.configure(auto_start=False, registry=reg)
    try:
        t = 100.0
        prof_mod.record_write_buckets(
            enq=t,
            gate_start=t + 0.001,
            gate_acq=t + 0.003,
            dispatch=t + 0.004,
            thread_start=t + 0.006,
            thread_done=t + 0.016,
            resolved=t + 0.017,
            finalize_secs=0.004,
        )
        total = 0.0
        from corrosion_tpu.runtime.profiler import WRITE_BUCKETS

        for bucket in WRITE_BUCKETS:
            h = reg.histogram("corro.write.profile.seconds", bucket=bucket)
            assert h.count == 1, bucket
            total += h.total
        wall = reg.histogram("corro.write.profile.seconds", bucket="wall")
        assert wall.count == 1
        # the five buckets PARTITION the wall (to fp rounding)
        assert abs(total - wall.total) < 1e-9

        # a reordered stamp chain is refused, not banked as garbage
        prof_mod.record_write_buckets(
            enq=t, gate_start=t - 1.0, gate_acq=t, dispatch=t,
            thread_start=t, thread_done=t, resolved=t, finalize_secs=0.0,
        )
        assert wall.count == 1
    finally:
        prof_mod.configure()


# -- capture + hotspots -----------------------------------------------------


def test_capture_and_hotspots_are_bounded():
    reg = Registry()
    p = Profiler(window_secs=600.0, registry=reg)
    for i in range(30):
        p.ring.add_sample("worker;-;a.py:f%d" % i)
    for _ in range(50):
        p.ring.add_sample("store;-;store/crdt.py:commit")
    cap = p.capture("alert_commit-stall", top=10)
    assert cap["samples"] == 80
    assert len(cap["folded"]) <= 40  # 4 * top
    assert len(cap["top_self"]) == 10
    assert cap["top_self"][0]["frame"] == "store/crdt.py:commit"
    assert reg.counter("corro.profile.captures.total").value == 1

    spots = p.hotspots(top=3)
    assert len(spots) == 3
    assert spots[0] == {"frame": "store/crdt.py:commit", "samples": 50}


def test_loop_task_names_ride_the_fold(event_loop=None):
    import asyncio

    async def scenario():
        p = Profiler(window_secs=600.0, registry=Registry())
        p.register_loop_coldpath()

        async def busy():
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.05:
                pass  # hold the loop so samples land inside this task

        task = asyncio.get_running_loop().create_task(
            busy(), name="hot-task"
        )
        # sample from a worker thread while the named task runs
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                p.sample_once()

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        try:
            await task
        finally:
            stop.set()
            th.join(timeout=5.0)
        return p.folded()

    folded = asyncio.run(scenario())
    named = {k: v for k, v in folded.items() if k.startswith("loop;hot-task;")}
    assert named, folded

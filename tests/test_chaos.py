"""Chaos-engine fault injection: the store shim, the zombie peer, and
the degradation disciplines the r18 matrix forced (each regression test
names the scenario that found its bug).

Layers covered here (the full matrix lives in scripts/traffic_sim.py →
TRAFFIC_SIM.json; its tiny-shape replica in tests/test_traffic_sim.py):

- STORE: transient SQLITE_BUSY during a group commit fails ONLY the
  affected writer (savepoint isolation proven under injected faults,
  not just claimed) and leaves the store writable; an injected I/O
  error at COMMIT surfaces typed to every writer and the next commit
  succeeds.
- PROCESS: a zombie peer (sockets open, loop stalled) trips the r17
  PeerCircuit breaker instead of stalling sync rounds — the
  timeout-discipline deadlines are what turn the hang into a counted
  failure.
- ANNOUNCER: the zombie-node scenario's orphaning bug — an eviction
  mid-steady-sleep left a node silent for the rest of its 300 s
  announce period; the announce_wake event must end that sleep the
  moment the SWIM view collapses to self.
- CLIENT: a mid-stream agent restart surfaces a TYPED retryable error
  through the capped full-jitter reconnect loop, never a hang.
"""

import asyncio
import contextlib
import json
import sqlite3
import time

import aiohttp
import pytest

from corrosion_tpu.agent import syncer
from corrosion_tpu.agent.run import make_broadcastable_changes, shutdown
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.chaos.faults import CENSUS, StoreFaults
from corrosion_tpu.chaos.scenarios import ChaosEngine, Scenario, zombie_node
from corrosion_tpu.client import ClientError, CorrosionApiClient
from corrosion_tpu.net.mem import MemNetwork

from tests.test_agent import TEST_SCHEMA, boot, count_rows, wait_until


def test_group_commit_busy_fault_fails_only_affected_writer():
    """sick-disk scenario class: a transient SQLITE_BUSY raised on one
    writer's statement mid-group-commit aborts THAT writer's savepoint
    alone — its 7 batchmates commit, and the store stays writable."""

    async def main():
        net = MemNetwork(seed=41)
        a = await boot(net, "sick-a")
        try:
            doomed_error = {}

            def writer(i):
                def fn(tx):
                    if i == 3:
                        # deterministic injection through the real shim:
                        # every statement of THIS writer's sub-tx fails
                        a.store.chaos = StoreFaults(statement_busy_rate=1.0)
                    try:
                        return [tx.execute(
                            "INSERT INTO tests (id, text) VALUES (?, ?)",
                            [100 + i, f"w{i}"],
                        )]
                    finally:
                        a.store.chaos = None
                return fn

            async def submit(i):
                try:
                    return await make_broadcastable_changes(a, writer(i))
                except sqlite3.OperationalError as e:
                    doomed_error[i] = e
                    return None

            results = await asyncio.gather(*(submit(i) for i in range(8)))
            ok = [r for r in results if r is not None]
            assert len(ok) == 7, f"exactly one writer must fail, got {results}"
            assert list(doomed_error) == [3]
            assert "chaos-injected" in str(doomed_error[3])
            # the store is still writable after the fault
            res = await make_broadcastable_changes(
                a,
                lambda tx: [tx.execute(
                    "INSERT INTO tests (id, text) VALUES (?, ?)", [999, "ok"],
                )],
            )
            assert res.version > 0
            assert count_rows(a) == 8  # 7 survivors + the follow-up
        finally:
            await shutdown(a)

    asyncio.run(main())


def test_commit_io_error_is_typed_and_transient():
    """sick-disk scenario class: an injected disk I/O error at COMMIT
    surfaces as a typed sqlite error to the writer; clearing the fault
    leaves the store fully writable (no wedged lock, no poisoned
    connection)."""

    async def main():
        net = MemNetwork(seed=43)
        a = await boot(net, "sick-b")
        try:
            a.store.chaos = StoreFaults(commit_io_error_rate=1.0)
            with pytest.raises(sqlite3.OperationalError, match="chaos-injected"):
                await make_broadcastable_changes(
                    a,
                    lambda tx: [tx.execute(
                        "INSERT INTO tests (id, text) VALUES (?, ?)",
                        [1, "doomed"],
                    )],
                )
            a.store.chaos = None
            res = await make_broadcastable_changes(
                a,
                lambda tx: [tx.execute(
                    "INSERT INTO tests (id, text) VALUES (?, ?)", [2, "ok"],
                )],
            )
            assert res.version > 0
            assert count_rows(a) == 1
        finally:
            a.store.chaos = None
            await shutdown(a)

    asyncio.run(main())


def test_zombie_peer_trips_circuit_breaker_not_the_round():
    """zombie-node scenario: a peer whose sockets stay open while its
    loop is stalled must cost counted recv timeouts that open the r17
    PeerCircuit breaker — and the sync loop must keep completing rounds
    (no unbounded stall) while the zombie is in the peer set."""

    async def main():
        saved = (syncer.RECV_TIMEOUT, syncer.OPEN_TIMEOUT)
        syncer.RECV_TIMEOUT, syncer.OPEN_TIMEOUT = 0.5, 0.5
        net = MemNetwork(seed=47)
        from corrosion_tpu.agent.membership import SwimConfig

        # suspicion window longer than the zombie window: the peer must
        # STAY in the member set so sync keeps dialing it — the breaker,
        # not eviction, is what this test exercises
        gentle = SwimConfig(probe_period=0.25, probe_rtt=0.1,
                            suspicion_mult=16)

        def tune(cfg):
            cfg.sync.circuit_reset_secs = 2.0

        a = await boot(net, "za", cfg=_tuned(tune, "za"))
        a.membership.config = gentle
        b = await boot(net, "zb", cfg=_tuned(tune, "zb", bootstrap=("za",)))
        b.membership.config = gentle
        try:
            assert await wait_until(
                lambda: a.membership.cluster_size == 2
                and b.membership.cluster_size == 2
            )
            rounds0 = _peek("corro.sync.client.rounds")
            net.zombie("zb")

            def circuit_open():
                c = a.sync_circuits.get(b.actor_id)
                return c is not None and not c.allows(time.monotonic())

            assert await wait_until(circuit_open, timeout=30), (
                "zombie peer never opened its circuit"
            )
            # rounds kept completing while the zombie was dialed: the
            # deadline turned each dead session into a bounded failure
            assert await wait_until(
                lambda: _peek("corro.sync.client.rounds") > rounds0 + 1,
                timeout=20,
            ), "sync rounds stalled behind the zombie"

            # heal: breaker half-opens after reset and sync repairs
            net.restore("zb")
            await make_broadcastable_changes(
                b,
                lambda tx: [tx.execute(
                    "INSERT INTO tests (id, text) VALUES (?, ?)", [7, "post"],
                )],
            )
            assert await wait_until(
                lambda: count_rows(a, "id = 7") == 1, timeout=30
            ), "cluster never repaired after zombie restore"
        finally:
            syncer.RECV_TIMEOUT, syncer.OPEN_TIMEOUT = saved
            await shutdown(b)
            await shutdown(a)

    asyncio.run(main())


def test_isolation_wakes_announcer_from_steady_sleep():
    """zombie-node scenario regression (the r18 orphaning bug): with a
    healthy cluster the announcer sleeps announce_steady_period (300 s
    default).  A zombie window long enough for mutual eviction used to
    leave the node SILENT for the rest of that sleep — no probes
    (nothing left to probe), no announces — an orphan for minutes after
    the network healed.  The announce_wake event must end the sleep the
    moment the SWIM view collapses to self, so rejoin rides the
    jittered ramp instead of the steady period."""

    async def main():
        net = MemNetwork(seed=53)
        from corrosion_tpu.agent.membership import SwimConfig

        # fast eviction + fast announce ramp, but the STEADY period
        # stays at its 300 s default — the pre-fix behavior would park
        # the announcer there and fail the rejoin bound below
        fast = SwimConfig(
            probe_period=0.05, probe_rtt=0.02, suspicion_mult=1.0,
            announce_backoff_start=0.2, announce_backoff_max=1.0,
        )
        a = await boot(net, "wa")
        a.membership.config = fast
        b = await boot(net, "wb", bootstrap=("wa",))
        b.membership.config = fast
        try:
            assert await wait_until(
                lambda: a.membership.cluster_size == 2
                and b.membership.cluster_size == 2
            )
            # let both announcers enter their steady-period sleep
            await asyncio.sleep(0.3)
            net.zombie("wb")
            # mutual eviction: both views collapse to self
            assert await wait_until(
                lambda: a.membership.cluster_size == 1
                and b.membership.cluster_size == 1,
                timeout=20,
            ), "zombie window never evicted"
            net.restore("wb")
            t0 = time.monotonic()
            assert await wait_until(
                lambda: a.membership.cluster_size == 2
                and b.membership.cluster_size == 2,
                timeout=15,
            ), "isolated node never rejoined (announcer still asleep?)"
            assert time.monotonic() - t0 < 15.0
        finally:
            await shutdown(b)
            await shutdown(a)

    asyncio.run(main())


def test_client_restart_surfaces_typed_error_not_hang():
    """client.py audit pin: an agent restart mid-subscription must
    surface a TYPED retryable error through the capped full-jitter
    reconnect loop within a bounded wall — never a hang."""

    async def main():
        net = MemNetwork(seed=59)
        a = await boot(net, "ca")
        api = ApiServer(a)
        a.config.api.bind_addr = ["127.0.0.1:0"]
        await api.start()
        client = CorrosionApiClient(api.addrs[0])
        try:
            stream = client.subscribe(
                "SELECT id, text FROM tests", skip_rows=True
            )
            stream._max_retries = 2  # keep the capped loop fast in-suite
            it = stream.__aiter__()

            async def first_event():
                await make_broadcastable_changes(
                    a,
                    lambda tx: [tx.execute(
                        "INSERT INTO tests (id, text) VALUES (?, ?)",
                        [1, "live"],
                    )],
                )
                return await it.__anext__()

            ev = await asyncio.wait_for(first_event(), 10)
            assert "change" in ev or "columns" in ev
            # the /v1/status chaos census rides the same live API: with
            # no drill running it must read inactive (the operator's
            # drill-vs-outage discriminator)
            session = await client._ensure()
            async with session.get(f"{client.base}/v1/status") as resp:
                status = json.loads(await resp.text())
            assert status["chaos"]["active"] is False
            assert status["chaos"]["scenario"] is None
            # kill the serving side mid-stream
            await api.stop()
            with pytest.raises(
                (aiohttp.ClientError, ConnectionError, ClientError,
                 StopAsyncIteration)
            ):
                # typed within the retry budget (2 retries × ≤2 s full
                # jitter) — the 20 s wait_for is the hang detector
                await asyncio.wait_for(_drain(it), 20)
        finally:
            await client.close()
            with contextlib.suppress(Exception):
                await api.stop()
            await shutdown(a)

    asyncio.run(main())


def test_chaos_census_marks_drills():
    """/v1/status discriminator: an applied scenario registers in the
    process-global census (scenario id + per-injection summaries) and
    restore() clears it."""

    async def main():
        net = MemNetwork(seed=61)
        engine = ChaosEngine()
        assert CENSUS.snapshot()["active"] is False
        await engine.apply(
            Scenario("drill-1", [zombie_node(net, "nowhere")])
        )
        snap = CENSUS.snapshot()
        assert snap["active"] is True
        assert snap["scenario"] == "drill-1"
        assert any("zombie" in s for s in snap["injections"].values())
        assert net.is_zombie("nowhere")
        await engine.restore()
        snap = CENSUS.snapshot()
        assert snap["active"] is False
        assert snap["injections"] == {}
        assert not net.is_zombie("nowhere")

    asyncio.run(main())


# -- helpers ----------------------------------------------------------------


def _tuned(tune, addr, bootstrap=()):
    from tests.test_agent import fast_config

    cfg = fast_config(addr, bootstrap)
    tune(cfg)
    return cfg


def _peek(name: str, **labels) -> float:
    from corrosion_tpu.runtime.metrics import METRICS

    for _kind, sname, slabels, value in METRICS.snapshot():
        if sname == name and slabels == labels:
            return value
    return 0.0


async def _drain(it):
    while True:
        await it.__anext__()

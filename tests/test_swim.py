"""Batched SWIM kernel: convergence, failure detection, refutation, churn.

Counterpart of the reference's SWIM-runtime expectations (foca semantics
driven via `broadcast/mod.rs:121-386`): members discover each other from
seeds, dead members get suspected then declared down, live members refute
wrongful suspicion by incarnation bump, and restarts rejoin cleanly.
"""

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.models.cluster import ClusterSim
from corrosion_tpu.ops import swim


def test_key_encoding_precedence():
    # higher incarnation beats any status; same incarnation: down>suspect>alive
    a0 = swim.make_key(0, swim.PREC_ALIVE)
    s0 = swim.make_key(0, swim.PREC_SUSPECT)
    d0 = swim.make_key(0, swim.PREC_DOWN)
    a1 = swim.make_key(1, swim.PREC_ALIVE)
    assert 0 < a0 < s0 < d0 < a1
    assert swim.key_inc(jnp.int32(a1)) == 1
    assert swim.key_prec(jnp.int32(s0)) == swim.PREC_SUSPECT
    assert not swim.key_known(jnp.int32(0))


def test_bootstrap_convergence_small():
    sim = ClusterSim(32, seed=3)
    stable = sim.run_until_stable(coverage_target=1.0, max_ticks=120)
    assert stable is not None, f"no convergence: {sim.stats()}"
    s = sim.stats()
    assert s["false_positive"] == 0.0


def test_failure_detection_and_no_false_positives():
    sim = ClusterSim(48, seed=4)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)
    for m in (7, 23):
        sim.crash(m)
    took = sim.run_until_detected(detect_target=1.0, max_extra_ticks=120)
    assert took is not None, f"failures not detected: {sim.stats()}"
    s = sim.stats()
    assert s["false_positive"] == 0.0
    # detection latency should be within suspicion + probe windows
    assert took <= 60


def test_restart_rejoins():
    sim = ClusterSim(32, seed=5)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)
    sim.crash(11)
    assert sim.run_until_detected(detect_target=1.0, max_extra_ticks=120)
    sim.restart(11)  # renewed incarnation, like foca Identity::renew
    sim.step(80)
    s = sim.stats()
    assert s["coverage"] >= 0.999, s
    assert s["false_positive"] == 0.0, s


def test_message_loss_tolerated():
    sim = ClusterSim(32, seed=6, loss=0.10)
    stable = sim.run_until_stable(coverage_target=0.999, max_ticks=300)
    assert stable is not None
    # 10% loss may cause transient suspicion but refutation must clean up
    sim.step(40)
    s = sim.stats()
    assert s["false_positive"] <= 0.01, s


def test_deterministic_given_seed():
    a = ClusterSim(24, seed=7)
    b = ClusterSim(24, seed=7)
    a.step(20)
    b.step(20)
    assert jnp.array_equal(a.state.view, b.state.view)
    assert jnp.array_equal(a.state.buf_subj, b.state.buf_subj)


def test_refutation_bumps_incarnation():
    # force a wrongful suspicion: crash, let suspicion start, restart before
    # the down declaration propagates fully
    sim = ClusterSim(24, seed=8, suspicion_ticks=12)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=100)
    sim.crash(5)
    sim.step(6)  # probes fail, suspicion spreads, timers still running
    sim.restart(5)
    sim.step(60)
    s = sim.stats()
    assert s["coverage"] >= 0.999, s
    assert s["false_positive"] == 0.0, s
    assert int(sim.state.inc[5]) >= 1  # refuted or renewed


def test_hub_seed_mode():
    sim = ClusterSim(32, seed=9, seed_mode="hub")
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)


@pytest.mark.parametrize("n", [16, 64])
def test_view_monotonicity(n):
    """Views never regress: keys are monotone non-decreasing over ticks
    (the property that makes scatter-max delivery correct)."""
    sim = ClusterSim(n, seed=10)
    prev = sim.state.view
    for _ in range(15):
        sim.step()
        cur = sim.state.view
        assert bool(jnp.all(cur >= prev))
        prev = cur


def test_crash_of_seed_members():
    # killing all of a member's ring seeds must not strand it
    sim = ClusterSim(24, seed=11)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=100)
    for m in (1, 2, 3):  # member 0's seeds
        sim.crash(m)
    assert sim.run_until_detected(detect_target=1.0, max_extra_ticks=150)
    s = sim.stats()
    assert s["coverage"] >= 0.999

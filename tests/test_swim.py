"""Batched SWIM kernel: convergence, failure detection, refutation, churn.

Counterpart of the reference's SWIM-runtime expectations (foca semantics
driven via `broadcast/mod.rs:121-386`): members discover each other from
seeds, dead members get suspected then declared down, live members refute
wrongful suspicion by incarnation bump, and restarts rejoin cleanly.
"""

import jax
import jax.numpy as jnp
import pytest

from corrosion_tpu.models.cluster import ClusterSim
from corrosion_tpu.ops import swim


def test_key_encoding_precedence():
    # higher incarnation beats any status; same incarnation: down>suspect>alive
    a0 = swim.make_key(0, swim.PREC_ALIVE)
    s0 = swim.make_key(0, swim.PREC_SUSPECT)
    d0 = swim.make_key(0, swim.PREC_DOWN)
    a1 = swim.make_key(1, swim.PREC_ALIVE)
    assert 0 < a0 < s0 < d0 < a1
    assert swim.key_inc(jnp.int32(a1)) == 1
    assert swim.key_prec(jnp.int32(s0)) == swim.PREC_SUSPECT
    assert not swim.key_known(jnp.int32(0))


def test_bootstrap_convergence_small():
    sim = ClusterSim(32, seed=3)
    stable = sim.run_until_stable(coverage_target=1.0, max_ticks=120)
    assert stable is not None, f"no convergence: {sim.stats()}"
    s = sim.stats()
    assert s["false_positive"] == 0.0


def test_failure_detection_and_no_false_positives():
    sim = ClusterSim(48, seed=4)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)
    for m in (7, 23):
        sim.crash(m)
    took = sim.run_until_detected(detect_target=1.0, max_extra_ticks=120)
    assert took is not None, f"failures not detected: {sim.stats()}"
    s = sim.stats()
    assert s["false_positive"] == 0.0
    # detection latency should be within suspicion + probe windows
    assert took <= 60


def test_restart_rejoins():
    sim = ClusterSim(32, seed=5)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)
    sim.crash(11)
    assert sim.run_until_detected(detect_target=1.0, max_extra_ticks=120)
    sim.restart(11)  # renewed incarnation, like foca Identity::renew
    sim.step(80)
    s = sim.stats()
    assert s["coverage"] >= 0.999, s
    assert s["false_positive"] == 0.0, s


def test_message_loss_tolerated():
    sim = ClusterSim(32, seed=6, loss=0.10)
    stable = sim.run_until_stable(coverage_target=0.999, max_ticks=300)
    assert stable is not None
    # 10% loss causes transient suspicions; refutation must keep cleaning
    # them up — sample a few windows rather than one instant (a single
    # in-flight suspicion at n=32 is 0.0101 of all pairs)
    s = None
    for _ in range(5):
        # 40 single-tick dispatches reuse the already-compiled tick —
        # a step(40) scan was one more ~4 s XLA specialization for
        # milliseconds of n=32 execution (r16 budget audit)
        for _ in range(40):
            sim.step(1)
        s = sim.stats()
        if s["false_positive"] <= 0.01:
            break
    assert s["false_positive"] <= 0.01, s


def test_deterministic_given_seed():
    a = ClusterSim(24, seed=7)
    b = ClusterSim(24, seed=7)
    a.step(20)
    b.step(20)
    assert jnp.array_equal(a.state.view, b.state.view)
    assert jnp.array_equal(a.state.buf_subj, b.state.buf_subj)


def test_refutation_bumps_incarnation():
    # force a wrongful suspicion: crash, let suspicion start, restart before
    # the down declaration propagates fully
    sim = ClusterSim(24, seed=8, suspicion_ticks=12)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=100)
    sim.crash(5)
    # single-tick stepping reuses the tick program run_until_stable
    # already compiled — step(6)/step(60) each minted a NEW scan-length
    # specialization, ~7 s of XLA:CPU compile for n=24 execution that
    # takes milliseconds (r16 budget audit)
    for _ in range(6):  # probes fail, suspicion spreads, timers running
        sim.step(1)
    sim.restart(5)
    for _ in range(60):
        sim.step(1)
    s = sim.stats()
    assert s["coverage"] >= 0.999, s
    assert s["false_positive"] == 0.0, s
    assert int(sim.state.inc[5]) >= 1  # refuted or renewed


def test_hub_seed_mode():
    sim = ClusterSim(32, seed=9, seed_mode="hub")
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)


@pytest.mark.parametrize("n", [16, 64])
def test_view_monotonicity(n):
    """Views never regress: keys are monotone non-decreasing over ticks
    (the property that makes scatter-max delivery correct)."""
    sim = ClusterSim(n, seed=10)
    prev = sim.state.view
    for _ in range(15):
        sim.step()
        cur = sim.state.view
        assert bool(jnp.all(cur >= prev))
        prev = cur


def test_crash_of_seed_members():
    # killing all of a member's ring seeds must not strand it
    sim = ClusterSim(24, seed=11)
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=100)
    for m in (1, 2, 3):  # member 0's seeds
        sim.crash(m)
    assert sim.run_until_detected(detect_target=1.0, max_extra_ticks=150)
    s = sim.stats()
    assert s["coverage"] >= 0.999


def test_partition_split_brain_and_heal():
    """Per-link partition simulation (r2 weakness: iid loss alone cannot
    model partitions). Split the cluster in half: each side declares the
    other down while staying FP-free internally; heal, and refutations
    clear every false positive."""
    n = 64
    params = swim.SwimParams(n=n, feeds_per_tick=4, feed_entries=16)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    # converge
    for _ in range(6):
        rng, key = jax.random.split(rng)
        state = swim.tick_n(state, key, params, 25)
    assert swim.membership_stats(state)["coverage"] >= 0.999

    # split into two halves
    groups = jnp.where(jnp.arange(n) < n // 2, 0, 1)
    state = swim.set_partition(state, groups)
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim.tick_n(state, key, params, 10)

    prec = swim.key_prec(state.view)
    known = state.view > 0
    half = n // 2
    # cross-partition entries: suspected or downed (no acks cross the cut)
    cross_down = (known & (prec == swim.PREC_DOWN))[:half, half:]
    assert float(jnp.mean(cross_down)) > 0.5, float(jnp.mean(cross_down))
    # within-partition entries stay alive-known: no internal collateral
    within_a = (known & (prec == swim.PREC_ALIVE))[:half, :half]
    eye = jnp.eye(half, dtype=bool)
    assert bool(jnp.all(within_a | eye))

    # heal: refutations must clear the false positives
    state = swim.set_partition(state, jnp.zeros(n, jnp.int32))
    for _ in range(12):
        rng, key = jax.random.split(rng)
        state = swim.tick_n(state, key, params, 10)
    stats = swim.membership_stats(state)
    assert stats["false_positive"] == 0.0, stats
    assert stats["coverage"] >= 0.999, stats


def test_partition_pview_split_brain_and_heal():
    """Same split-brain behavior with the bounded partial-view kernel."""
    from corrosion_tpu.ops import swim_pview

    n, k = 256, 64
    pp = swim_pview.PViewParams(n=n, slots=k, feeds_per_tick=4, feed_entries=16)
    state = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    for _ in range(6):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 25)
    assert swim_pview.membership_stats(state, pp)["false_positive"] == 0.0

    groups = jnp.where(jnp.arange(n) < n // 2, 0, 1)
    state = swim_pview.set_partition(state, groups)
    for _ in range(8):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    # false positives appear (cross-partition suspicions of live members)
    assert swim_pview.membership_stats(state, pp)["false_positive"] > 0.0

    state = swim_pview.set_partition(state, jnp.zeros(n, jnp.int32))
    for _ in range(12):
        rng, key = jax.random.split(rng)
        state = swim_pview.tick_n(state, key, pp, 10)
    stats = swim_pview.membership_stats(state, pp)
    assert stats["false_positive"] == 0.0, stats
    assert stats["min_in_degree"] > 0, stats


def test_feeds_disabled_config_still_ticks():
    """feed_entries>0 with feeds_per_tick=0 is a valid config (gossip
    only); the bootstrap-seed exchange must not depend on the feed
    loop's locals."""
    params = swim.SwimParams(n=16, feeds_per_tick=0, feed_entries=8)
    state = swim.init_state(params, jax.random.PRNGKey(0))
    out = swim.tick(state, jax.random.PRNGKey(1), params)
    assert int(out.t) == 1

    from corrosion_tpu.ops import swim_pview

    pp = swim_pview.PViewParams(
        n=16, slots=16, feeds_per_tick=0, feed_entries=8
    )
    ps = swim_pview.init_state(pp, jax.random.PRNGKey(0))
    out = swim_pview.tick(ps, jax.random.PRNGKey(1), pp)
    assert int(out.t) == 1


def test_view_key_saturation_preserves_precedence():
    """The int16 view clamp must never change a key's precedence class:
    a saturated ALIVE key stays ALIVE, DOWN stays DOWN (review finding:
    a min()-style clamp decoded as SUSPECT and re-registered as improved
    forever). In-range keys pass through untouched."""
    import numpy as np

    for prec in (swim.PREC_ALIVE, swim.PREC_SUSPECT, swim.PREC_DOWN):
        # in-range: identity
        k = swim.make_key(swim.INC_CAP, prec)
        stored = int(swim.to_view_key(jnp.int32(k)))
        assert stored == k
        assert int(swim.key_prec(jnp.int16(stored))) == prec
        # out-of-range: saturates, same precedence
        k_over = swim.make_key(swim.INC_CAP + 500, prec)
        stored = int(swim.to_view_key(jnp.int32(k_over)))
        assert int(swim.key_prec(jnp.int16(stored))) == prec
        assert stored <= np.iinfo(np.int16).max
        assert stored > 0


def test_refutation_incarnation_caps():
    """Refutation increments saturate at INC_CAP so generated keys always
    fit the int16 view: below the cap a suspected member refutes normally
    (bumps inc, self entry returns to ALIVE); AT the cap the bump cannot
    exceed the suspicion's incarnation, so the suspicion stands — the
    accepted saturation trade-off (reaching inc 8189 needs thousands of
    refutation cycles; real SWIM incarnations stay in the tens)."""
    params = swim.SwimParams(n=8)

    def suspected_at(inc0):
        state = swim.init_state(params, jax.random.PRNGKey(0))
        state = state._replace(
            inc=state.inc.at[1].set(inc0),
            view=state.view.at[1, 1].set(
                swim.to_view_key(
                    jnp.int32(swim.make_key(inc0, swim.PREC_SUSPECT))
                )
            ),
        )
        return swim.tick(state, jax.random.PRNGKey(1), params)

    # below the cap: refutation bumps inc and restores ALIVE precedence
    out = suspected_at(swim.INC_CAP - 10)
    assert int(out.inc[1]) == swim.INC_CAP - 9
    assert int(swim.key_prec(out.view[1, 1])) == swim.PREC_ALIVE

    # at the cap: inc saturates and the suspicion stands
    out = suspected_at(swim.INC_CAP)
    assert int(out.inc[1]) == swim.INC_CAP
    assert int(swim.key_prec(out.view[1, 1])) == swim.PREC_SUSPECT


def test_fingers_bootstrap_converges_faster_than_ring():
    """The Chord-style finger bootstrap (power-of-two offsets) is the
    bench's devcluster topology: its expander bootstrap graph must (a)
    seed exactly the finger entries, and (b) converge a boot in fewer
    ticks than the 3-neighbor ring at the same feed bandwidth — the
    early epidemic is partner-correlation bound (PROFILE.md)."""
    import math

    n = 512
    params = swim.SwimParams(n=n, feeds_per_tick=2, feed_entries=32)
    st = swim.init_state(params, jax.random.PRNGKey(0), seed_mode="fingers")
    row0 = st.view[0]
    known = {int(i) for i in jnp.nonzero(row0)[0]}
    fingers = {0} | {2**j % n for j in range(int(math.log2(n)) + 1)}
    assert known == fingers, (known, fingers)

    def ticks_to(target, state):
        rng = jax.random.PRNGKey(1)
        for t in range(1, 41):
            rng, key = jax.random.split(rng)
            state = swim.tick_n_donated(state, key, params, 5)
            s = swim.membership_stats(state)
            assert s["false_positive"] == 0.0
            if s["coverage"] >= target:
                return t * 5
        return 10_000

    t_fingers = ticks_to(
        0.999, swim.init_state(params, jax.random.PRNGKey(0), seed_mode="fingers")
    )
    t_ring = ticks_to(
        0.999, swim.init_state(params, jax.random.PRNGKey(0), seed_mode="ring")
    )
    assert t_fingers < t_ring, (t_fingers, t_ring)
    assert t_fingers < 10_000, "fingers boot never converged"


def test_shift_gossip_converges_detects_and_refutes():
    """gossip_mode="shift" (per-tick global-offset fanout, sortless
    delivery): same protocol guarantees as "pick" — bootstrap
    convergence, dead-member detection with zero false positives, and
    clean restart — on the row-gather delivery path."""
    sim = ClusterSim(48, seed=4, gossip_mode="shift")
    assert sim.run_until_stable(coverage_target=0.999, max_ticks=120)
    s = sim.stats()
    assert s["false_positive"] == 0.0
    for m in (7, 23):
        sim.crash(m)
    took = sim.run_until_detected(detect_target=1.0, max_extra_ticks=120)
    assert took is not None, f"failures not detected: {sim.stats()}"
    s = sim.stats()
    assert s["false_positive"] == 0.0
    assert took <= 60
    sim.restart(7)
    sim.step(80)
    s = sim.stats()
    assert s["coverage"] >= 0.999, s
    assert s["false_positive"] == 0.0, s


def test_shift_gossip_message_loss_tolerated():
    sim = ClusterSim(32, seed=6, gossip_mode="shift", loss=0.2)
    stable = sim.run_until_stable(coverage_target=0.999, max_ticks=300)
    assert stable is not None, f"no convergence under loss: {sim.stats()}"
    assert sim.stats()["false_positive"] == 0.0


def test_device_loop_matches_host_loop_convergence():
    """run_until_stable_device (on-device while_loop) must reach the
    same convergence verdict as the host-driven loop, with its tick
    count aligned to check_every granularity and zero false positives."""
    a = ClusterSim(64, seed=11)
    b = ClusterSim(64, seed=11)
    ta = a.run_until_stable(coverage_target=0.999, max_ticks=200)
    b.warm_device_loop(0.999, 200, 5)
    tb = b.run_until_stable_device(
        coverage_target=0.999, max_ticks=200, check_every=5
    )
    assert ta is not None and tb is not None
    sa, sb = a.stats(), b.stats()
    assert sb["coverage"] >= 0.999
    assert sb["false_positive"] == 0.0
    # same kernel, same seed: device loop may exit a few ticks off the
    # host cadence but must land in the same convergence regime
    assert abs(ta - tb) <= 25, (ta, tb)
    assert int(b.state.t) == tb


def test_device_loop_nonconvergence_returns_none():
    # loss=1.0 makes non-convergence deterministic (under the shift
    # default, 64 members can genuinely converge inside 5 lossless
    # ticks — the old premise)
    sim = ClusterSim(64, seed=12, loss=1.0)
    out = sim.run_until_stable_device(
        coverage_target=1.0, max_ticks=5, check_every=5
    )
    assert out is None
    assert sim.ticks == 5

// SQLite loadable extension: native CRDT hot-path functions.
//
// The reference's single native component is the cr-sqlite C extension
// (loaded in klukai-types/src/sqlite.rs:125-143); this is our equivalent
// native layer. The write-capture triggers call crdt_pack() once per
// mutated row, so pk packing is the hottest per-write scalar op — doing
// it in C++ keeps Python out of the trigger path entirely.
//
// Functions:
//   crdt_pack(v1, v2, ...)  -> BLOB   pk encoding, byte-compatible with
//                                     cr-sqlite (see types/pack.py)
//   crdt_unpack_n(blob)     -> INT    column count of a packed pk
//   crdt_cmp(a, b)          -> INT    -1/0/1 cross-type value order
//                                     (NULL < numeric < TEXT < BLOB) —
//                                     the LWW "largest value wins"
//                                     tie-break on equal col_version
//
// Build: g++ -O2 -fPIC -shared (see corrosion_tpu/native.py).

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "sqlite3ext.h"
SQLITE_EXTENSION_INIT1

namespace {

constexpr uint8_t TYPE_INTEGER = 1;
constexpr uint8_t TYPE_REAL = 2;
constexpr uint8_t TYPE_TEXT = 3;
constexpr uint8_t TYPE_BLOB = 4;
constexpr uint8_t TYPE_NULL = 5;

// Bytes occupied by the two's-complement u64 pattern, matching the
// reference's byte-mask probing (pubsub.rs:2315-2340): negatives take 8,
// zero takes 0 — plus the sign-boundary widening deviation (see
// types/pack.py _num_bytes_needed): a positive value whose top encoded
// bit would be set gets one extra byte so sign-extending decode
// round-trips (the reference drops 128..255-band integer/length pks).
int num_bytes_needed(int64_t val) {
  uint64_t u = static_cast<uint64_t>(val);
  for (int n = 8; n >= 1; --n) {
    if ((u >> ((n - 1) * 8)) & 0xFF) {
      if (val > 0 && n < 8 && ((u >> ((n - 1) * 8)) & 0x80)) return n + 1;
      return n;
    }
  }
  return 0;
}

void put_int_be(std::string& buf, int64_t val, int nbytes) {
  uint64_t u = static_cast<uint64_t>(val);
  for (int i = nbytes - 1; i >= 0; --i) {
    buf.push_back(static_cast<char>((u >> (i * 8)) & 0xFF));
  }
}

void crdt_pack(sqlite3_context* ctx, int argc, sqlite3_value** argv) {
  if (argc > 0xFF) {
    sqlite3_result_error(ctx, "too many columns to pack", -1);
    return;
  }
  std::string buf;
  buf.reserve(1 + argc * 10);
  buf.push_back(static_cast<char>(argc));
  for (int i = 0; i < argc; ++i) {
    sqlite3_value* v = argv[i];
    switch (sqlite3_value_type(v)) {
      case SQLITE_NULL:
        buf.push_back(static_cast<char>(TYPE_NULL));
        break;
      case SQLITE_INTEGER: {
        int64_t val = sqlite3_value_int64(v);
        int n = num_bytes_needed(val);
        buf.push_back(static_cast<char>((n << 3) | TYPE_INTEGER));
        put_int_be(buf, val, n);
        break;
      }
      case SQLITE_FLOAT: {
        double d = sqlite3_value_double(v);
        uint64_t bits;
        std::memcpy(&bits, &d, sizeof(bits));
        buf.push_back(static_cast<char>(TYPE_REAL));
        put_int_be(buf, static_cast<int64_t>(bits), 8);
        break;
      }
      case SQLITE_TEXT: {
        const unsigned char* s = sqlite3_value_text(v);
        int len = sqlite3_value_bytes(v);
        int n = len ? num_bytes_needed(len) : 0;
        buf.push_back(static_cast<char>((n << 3) | TYPE_TEXT));
        put_int_be(buf, len, n);
        buf.append(reinterpret_cast<const char*>(s), len);
        break;
      }
      case SQLITE_BLOB: {
        const void* b = sqlite3_value_blob(v);
        int len = sqlite3_value_bytes(v);
        int n = len ? num_bytes_needed(len) : 0;
        buf.push_back(static_cast<char>((n << 3) | TYPE_BLOB));
        put_int_be(buf, len, n);
        if (len) buf.append(reinterpret_cast<const char*>(b), len);
        break;
      }
      default:
        sqlite3_result_error(ctx, "unsupported value type", -1);
        return;
    }
  }
  sqlite3_result_blob64(ctx, buf.data(), buf.size(), SQLITE_TRANSIENT);
}

void crdt_unpack_n(sqlite3_context* ctx, int argc, sqlite3_value** argv) {
  if (argc != 1 || sqlite3_value_type(argv[0]) != SQLITE_BLOB) {
    sqlite3_result_error(ctx, "crdt_unpack_n expects one blob", -1);
    return;
  }
  int len = sqlite3_value_bytes(argv[0]);
  if (len < 1) {
    sqlite3_result_error(ctx, "empty pk buffer", -1);
    return;
  }
  const unsigned char* data =
      static_cast<const unsigned char*>(sqlite3_value_blob(argv[0]));
  sqlite3_result_int(ctx, data[0]);
}

int type_rank(int sqlite_type) {
  switch (sqlite_type) {
    case SQLITE_NULL: return 0;
    case SQLITE_INTEGER:
    case SQLITE_FLOAT: return 1;
    case SQLITE_TEXT: return 2;
    case SQLITE_BLOB: return 3;
  }
  return 4;
}

// Cross-type total order (types/values.py cmp_values): the LWW
// tie-break on equal col_version ("largest value wins", the semantics
// behind crsql_config_set('merge-equal-values', 1)).
void crdt_cmp(sqlite3_context* ctx, int argc, sqlite3_value** argv) {
  if (argc != 2) {
    sqlite3_result_error(ctx, "crdt_cmp expects two values", -1);
    return;
  }
  sqlite3_value *a = argv[0], *b = argv[1];
  int ta = sqlite3_value_type(a), tb = sqlite3_value_type(b);
  int ra = type_rank(ta), rb = type_rank(tb);
  if (ra != rb) {
    sqlite3_result_int(ctx, ra < rb ? -1 : 1);
    return;
  }
  int out = 0;
  if (ra == 0) {
    out = 0;
  } else if (ra == 1) {
    double da = sqlite3_value_double(a), db = sqlite3_value_double(b);
    if (ta == SQLITE_INTEGER && tb == SQLITE_INTEGER) {
      int64_t ia = sqlite3_value_int64(a), ib = sqlite3_value_int64(b);
      out = ia < ib ? -1 : (ia > ib ? 1 : 0);
    } else {
      out = da < db ? -1 : (da > db ? 1 : 0);
    }
  } else {
    int la = sqlite3_value_bytes(a), lb = sqlite3_value_bytes(b);
    const void* pa = ra == 2 ? static_cast<const void*>(sqlite3_value_text(a))
                             : sqlite3_value_blob(a);
    const void* pb = ra == 2 ? static_cast<const void*>(sqlite3_value_text(b))
                             : sqlite3_value_blob(b);
    int n = la < lb ? la : lb;
    int c = n ? std::memcmp(pa, pb, n) : 0;
    if (c != 0) {
      out = c < 0 ? -1 : 1;
    } else {
      out = la < lb ? -1 : (la > lb ? 1 : 0);
    }
  }
  sqlite3_result_int(ctx, out);
}

}  // namespace

extern "C" int sqlite3_crdtext_init(sqlite3* db, char** pzErrMsg,
                                    const sqlite3_api_routines* pApi) {
  SQLITE_EXTENSION_INIT2(pApi);
  (void)pzErrMsg;
  int rc = sqlite3_create_function_v2(
      db, "crdt_pack", -1, SQLITE_UTF8 | SQLITE_DETERMINISTIC, nullptr,
      crdt_pack, nullptr, nullptr, nullptr);
  if (rc != SQLITE_OK) return rc;
  rc = sqlite3_create_function_v2(
      db, "crdt_unpack_n", 1, SQLITE_UTF8 | SQLITE_DETERMINISTIC, nullptr,
      crdt_unpack_n, nullptr, nullptr, nullptr);
  if (rc != SQLITE_OK) return rc;
  rc = sqlite3_create_function_v2(
      db, "crdt_cmp", 2, SQLITE_UTF8 | SQLITE_DETERMINISTIC, nullptr,
      crdt_cmp, nullptr, nullptr, nullptr);
  return rc;
}

// Native columnar CRDT merge: the batch decision loop of
// corrosion_tpu/store/crdt.py::_apply_batch (phase B) in C++.
//
// The reference's only native component is the cr-sqlite C extension whose
// merge rules run inside INSERT INTO crsql_changes
// (klukai-agent/src/agent/util.rs:703-1310 drives it); this library is our
// equivalent native CRDT layer for the remote-apply hot path: Python
// bulk-reads the local snapshot (phase A), hands the batch + snapshot to
// `crdt_merge_batch` as columnar arrays, and flushes the returned final
// plans with executemany (phase C).  Semantics are pinned to the Python
// decision loop by tests/test_crdt_batch.py (randomized equivalence across
// per-row / python-batched / native-batched).
//
// Decision rules mirrored exactly (column-level LWW with causal length):
//   ch.cl < local_cl                      -> lose (row-level dominance)
//   ch.cl > local_cl                      -> causal transition: clock rows
//       reset (every transition), data cells reset only on delete (even
//       cl); odd re-create keeps surviving cell values
//   ch.cl == local_cl (odd, non-sentinel) -> col_version compare; equal
//       col_version falls back to "largest value wins" over the current
//       cell value (crsql merge-equal-values)
//
// Value order matches types/values.py::cmp_values bit-for-bit, including
// Python's EXACT mixed int/float comparison (long double on x86-64 has a
// 64-bit mantissa, so int64 values convert exactly).
//
// Build: g++ -O2 -fPIC -shared (see corrosion_tpu/native.py).

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t VT_INTEGER = 1;
constexpr uint8_t VT_REAL = 2;
constexpr uint8_t VT_TEXT = 3;
constexpr uint8_t VT_BLOB = 4;
constexpr uint8_t VT_NULL = 5;

// out_flags bits (must match corrosion_tpu/store/crdt.py native glue)
constexpr uint8_t F_ROWCL = 1;    // row_cl upsert with out_row_cl[pk]
constexpr uint8_t F_CLEARED = 2;  // non-sentinel clock rows drop
constexpr uint8_t F_DELETE = 4;   // data row delete
constexpr uint8_t F_ENSURE = 8;   // data row ensure-exists

struct Value {
  uint8_t type;
  int64_t i;
  double r;
  const uint8_t* p;
  int64_t len;
};

int rank_of(uint8_t t) {
  switch (t) {
    case VT_NULL: return 0;
    case VT_INTEGER:
    case VT_REAL: return 1;
    case VT_TEXT: return 2;
    case VT_BLOB: return 3;
  }
  return 4;
}

// types/values.py::cmp_values: NULL < numeric < TEXT < BLOB; numerics
// compare exactly across int/float like Python (not via lossy double).
int cmp_values(const Value& a, const Value& b) {
  int ra = rank_of(a.type), rb = rank_of(b.type);
  if (ra != rb) return ra < rb ? -1 : 1;
  if (ra == 0) return 0;
  if (ra == 1) {
    if (a.type == VT_INTEGER && b.type == VT_INTEGER)
      return a.i < b.i ? -1 : (a.i > b.i ? 1 : 0);
    if (a.type == VT_REAL && b.type == VT_REAL)
      return a.r < b.r ? -1 : (a.r > b.r ? 1 : 0);
    long double la = a.type == VT_INTEGER ? (long double)a.i : (long double)a.r;
    long double lb = b.type == VT_INTEGER ? (long double)b.i : (long double)b.r;
    return la < lb ? -1 : (la > lb ? 1 : 0);
  }
  int64_t n = a.len < b.len ? a.len : b.len;
  int c = n ? std::memcmp(a.p, b.p, (size_t)n) : 0;
  if (c != 0) return c < 0 ? -1 : 1;
  return a.len < b.len ? -1 : (a.len > b.len ? 1 : 0);
}

struct ClockEnt {
  int64_t cv;
  uint32_t gen;
  int32_t val_idx;  // change index whose value is current, -1 = snapshot
};

struct CellEnt {
  uint32_t gen;
  int32_t idx;  // winning change index (value + clock_entry source)
};

inline uint64_t keyof(int32_t pk, int32_t cid) {
  return ((uint64_t)(uint32_t)pk << 32) | (uint32_t)(cid + 1);
}

}  // namespace

extern "C" int crdt_merge_batch(
    // batch (one table), all arrays length n unless noted
    int32_t n, const int32_t* pk_id, const int32_t* cid_id,  // cid -1 = sentinel
    const int64_t* col_version, const int64_t* cl,
    const uint8_t* val_type, const int64_t* val_int, const double* val_real,
    const int64_t* val_off, const int64_t* val_len, const uint8_t* arena,
    // local snapshot
    int32_t n_pks, const int64_t* local_cl,
    int32_t n_clock, const int32_t* ck_pk, const int32_t* ck_cid,
    const int64_t* ck_cv,
    // prefetched current cell values for tie candidates
    int32_t n_disk, const int32_t* dk_pk, const int32_t* dk_cid,
    const uint8_t* dk_type, const int64_t* dk_int, const double* dk_real,
    const int64_t* dk_off, const int64_t* dk_len, const uint8_t* dk_arena,
    // outputs
    uint8_t* win,                               // [n]
    int64_t* out_row_cl, uint8_t* out_flags,    // [n_pks]
    int32_t* out_sentinel_idx,                  // [n_pks], -1 = none
    int32_t* out_cell_pk, int32_t* out_cell_cid, int32_t* out_cell_idx,
    int32_t* out_n_cells,                       // cell plans, capacity n
    int32_t* out_clock_pk, int32_t* out_clock_cid, int32_t* out_clock_idx,
    int32_t* out_n_clocks) {                    // clock plans, capacity n
  if (n < 0 || n_pks < 0 || n_clock < 0 || n_disk < 0) return 2;

  std::vector<int64_t> cur_cl(local_cl, local_cl + n_pks);
  std::vector<uint32_t> clock_gen(n_pks, 0), cell_gen(n_pks, 0);

  std::unordered_map<uint64_t, ClockEnt> clock;
  clock.reserve((size_t)(n_clock + n) * 2);
  for (int32_t i = 0; i < n_clock; ++i) {
    if (ck_pk[i] < 0 || ck_pk[i] >= n_pks) return 2;
    clock[keyof(ck_pk[i], ck_cid[i])] = ClockEnt{ck_cv[i], 0, -1};
  }
  std::unordered_map<uint64_t, int32_t> disk;
  disk.reserve((size_t)n_disk * 2);
  for (int32_t i = 0; i < n_disk; ++i) {
    if (dk_pk[i] < 0 || dk_pk[i] >= n_pks) return 2;
    disk[keyof(dk_pk[i], dk_cid[i])] = i;
  }
  std::unordered_map<uint64_t, CellEnt> cells;
  cells.reserve((size_t)n * 2);

  for (int32_t i = 0; i < n_pks; ++i) out_sentinel_idx[i] = -1;
  std::memset(out_flags, 0, (size_t)n_pks);
  std::memset(win, 0, (size_t)n);

  auto change_val = [&](int32_t i) -> Value {
    return Value{val_type[i], val_int[i], val_real[i],
                 arena + val_off[i], val_len[i]};
  };

  for (int32_t i = 0; i < n; ++i) {
    int32_t pk = pk_id[i];
    if (pk < 0 || pk >= n_pks) return 2;
    int32_t cid = cid_id[i];
    int64_t lcl = cur_cl[pk];
    int64_t ccl = cl[i];
    if (ccl < lcl) continue;
    bool w = false;
    if (ccl > lcl) {
      cur_cl[pk] = ccl;
      out_row_cl[pk] = ccl;
      out_flags[pk] |= F_ROWCL | F_CLEARED;
      clock_gen[pk]++;  // every transition resets clock rows + plans
      out_sentinel_idx[pk] = i;
      if ((ccl & 1) == 0) {
        cell_gen[pk]++;  // delete: pending cell writes die with the row
        out_flags[pk] |= F_DELETE;
        out_flags[pk] &= ~F_ENSURE;
        w = true;
      } else {
        out_flags[pk] |= F_ENSURE;
        if (cid >= 0) {
          clock[keyof(pk, cid)] =
              ClockEnt{col_version[i], clock_gen[pk], i};
          cells[keyof(pk, cid)] = CellEnt{cell_gen[pk], i};
        }
        w = true;
      }
    } else {
      if ((lcl & 1) == 0 || cid < 0) continue;
      auto it = clock.find(keyof(pk, cid));
      bool present = it != clock.end() && it->second.gen == clock_gen[pk];
      int64_t lcv = present ? it->second.cv : 0;
      if (col_version[i] < lcv) continue;
      if (col_version[i] == lcv && present) {
        // lazily-marshaled values: type 0 = not encoded; the Python glue
        // only skips values provably never compared, so hitting one means
        // fall back to the reference loop rather than guess
        if (val_type[i] == 0) return 1;
        Value cur;
        auto cit = cells.find(keyof(pk, cid));
        if (cit != cells.end() && cit->second.gen == cell_gen[pk]) {
          if (val_type[cit->second.idx] == 0) return 1;
          cur = change_val(cit->second.idx);
        } else {
          auto dit = disk.find(keyof(pk, cid));
          if (dit == disk.end()) return 1;  // caller falls back to Python
          int32_t d = dit->second;
          cur = Value{dk_type[d], dk_int[d], dk_real[d],
                      dk_arena + dk_off[d], dk_len[d]};
        }
        if (cmp_values(change_val(i), cur) <= 0) continue;
      }
      out_flags[pk] |= F_ENSURE;
      cells[keyof(pk, cid)] = CellEnt{cell_gen[pk], i};
      clock[keyof(pk, cid)] = ClockEnt{col_version[i], clock_gen[pk], i};
      w = true;
    }
    if (w) win[i] = 1;
  }

  // emit surviving plans; (pk, cid) recovered from the map keys
  int32_t nc = 0;
  for (const auto& kv : cells) {
    int32_t pk = (int32_t)(kv.first >> 32);
    if (kv.second.gen != cell_gen[pk]) continue;
    out_cell_pk[nc] = pk;
    out_cell_cid[nc] = (int32_t)(kv.first & 0xffffffffu) - 1;
    out_cell_idx[nc] = kv.second.idx;
    ++nc;
  }
  *out_n_cells = nc;
  int32_t nk = 0;
  for (const auto& kv : clock) {
    int32_t pk = (int32_t)(kv.first >> 32);
    if (kv.second.val_idx < 0 || kv.second.gen != clock_gen[pk]) continue;
    out_clock_pk[nk] = pk;
    out_clock_cid[nk] = (int32_t)(kv.first & 0xffffffffu) - 1;
    out_clock_idx[nk] = kv.second.val_idx;
    ++nk;
  }
  *out_n_clocks = nk;
  return 0;
}

// ---------------------------------------------------------------------------
// Native local-commit finalize (r24, write-path round 4): the phase-B
// decision loop of corrosion_tpu/store/crdt.py::finalize_group in C++.
//
// Python keeps phase A (the bulk clock/rows probes) and phase C (the
// grouped executemany flush); this function is handed the deduped
// (row, cid) order keys + deleted-row sets for EVERY item in the commit
// group as interned integer arrays, plus the probed cl/col_version
// snapshot, and returns (a) per-item change SPECS — row/cid/value
// index/col_version/causal length, seq implicit by position — and (b)
// the final rows-upsert / clock-clear / clock-put plans with Python
// dict insertion-order semantics (an overwritten key keeps its slot, a
// cleared-then-re-put key APPENDS — `del puts[cid]` then re-insert).
// Values never cross the boundary: a column spec carries the global
// order index, and the glue fetches the Python value + encodes via
// write_change_cells exactly as the columnar engine does.
//
// The walk is the sequential immediate-effect decision loop — the
// columnar engine's own in-order fallback, which coincides with its
// kind-split batches whenever every SENTINEL precedes its own row's
// column cells (the capture-plane convention) and with the percell
// reference always.  Bit-identity across all four engines is pinned by
// tests/test_finalize_batch.py.

// ---- finalize-parity markers (analysis/finalize_parity.py) ----------------
// These must stay in lockstep with the Python glue
// (store/crdt.py::_phase_b_native): the finalize-parity static rule
// pins the ABI version, the sentinel column id and the parity
// arithmetic below against the columnar engine at lint time.
#define FINALIZE_ABI_VERSION 1

namespace {

constexpr int32_t FIN_CID_SENTINEL = -1;  // the SENTINEL clock column id

struct PutEnt {
  int32_t row, cid, item, seq;
  int64_t cv;
  bool alive;
};

struct CvEnt {
  int64_t cv;
  uint32_t gen;
};

}  // namespace

extern "C" int crdt_finalize_batch(
    // group geometry: item i's deleted rows span del_off[i]..del_off[i+1],
    // its deduped order keys span ord_off[i]..ord_off[i+1] (cid -1 =
    // sentinel); both off arrays have n_items+1 entries
    int32_t n_items, const int32_t* del_off, const int32_t* del_row,
    const int32_t* ord_off, const int32_t* ord_row, const int32_t* ord_cid,
    // phase-A snapshot: per interned row the current causal length and
    // whether the row exists at all (cur_cl's absent-key distinction)
    int32_t n_rows, const int64_t* row_cl, const uint8_t* row_exists,
    // cv_state triples: (row, cid, col_version) from the clock probe
    int32_t n_cv, const int32_t* cv_row, const int32_t* cv_cid,
    const int64_t* cv_val,
    // outputs — caller allocates capacity n_del_total + n_ord_total for
    // every flat array (every delete/order key emits at most one spec,
    // and each plan grows at most once per spec)
    int32_t* out_spec_count,  // [n_items]
    int32_t* out_spec_row, int32_t* out_spec_cid,
    int32_t* out_spec_ord,  // global order index of the value, -1 = none
    int64_t* out_spec_cv, int64_t* out_spec_cl,
    int32_t* out_up_row, int64_t* out_up_cl, int32_t* out_n_up,
    int32_t* out_clear_row, int32_t* out_n_clear,
    int32_t* out_put_row, int32_t* out_put_cid, int64_t* out_put_cv,
    int32_t* out_put_item, int32_t* out_put_seq, int32_t* out_n_put) {
  if (n_items < 0 || n_rows < 0 || n_cv < 0) return 2;

  std::vector<int64_t> cl_live(row_cl, row_cl + n_rows);
  std::vector<uint8_t> exists(row_exists, row_exists + n_rows);
  std::vector<uint32_t> cv_gen(n_rows, 0);

  std::unordered_map<uint64_t, CvEnt> cvs;
  cvs.reserve((size_t)n_cv * 2);
  for (int32_t i = 0; i < n_cv; ++i) {
    if (cv_row[i] < 0 || cv_row[i] >= n_rows || cv_cid[i] < 0) return 2;
    cvs[keyof(cv_row[i], cv_cid[i])] = CvEnt{cv_val[i], 0};
  }

  // rows_up: dict-ordered upsert plan (overwrite in place, append new)
  std::vector<int32_t> up_pos(n_rows, -1);
  int32_t n_up = 0;
  auto rows_up_set = [&](int32_t row, int64_t cl) {
    if (up_pos[row] < 0) {
      up_pos[row] = n_up;
      out_up_row[n_up] = row;
      out_up_cl[n_up] = cl;
      ++n_up;
    } else {
      out_up_cl[up_pos[row]] = cl;
    }
  };

  // clock_clear: dict-ordered insert-once set
  std::vector<uint8_t> clear_seen(n_rows, 0);
  int32_t n_clear = 0;

  // clock_put with Python dict semantics: an existing key updates in
  // place; clear_clocks `del`s the row's non-sentinel keys so a later
  // re-put of the same (row, cid) APPENDS at the tail
  std::vector<PutEnt> puts;
  puts.reserve(64);
  std::unordered_map<uint64_t, int32_t> put_pos;
  std::vector<std::vector<int32_t>> row_puts(n_rows);
  auto put = [&](int32_t row, int32_t cid, int64_t cv, int32_t item,
                 int32_t seq) {
    uint64_t k = keyof(row, cid);
    auto it = put_pos.find(k);
    if (it != put_pos.end()) {
      PutEnt& e = puts[it->second];
      e.cv = cv;
      e.item = item;
      e.seq = seq;
    } else {
      put_pos[k] = (int32_t)puts.size();
      if (cid != FIN_CID_SENTINEL)
        row_puts[row].push_back((int32_t)puts.size());
      puts.push_back(PutEnt{row, cid, item, seq, cv, true});
    }
  };
  auto clear_clocks = [&](int32_t row) {
    if (!clear_seen[row]) {
      clear_seen[row] = 1;
      out_clear_row[n_clear++] = row;
    }
    cv_gen[row]++;  // cv_state.pop(row): snapshot + earlier puts die
    for (int32_t pos : row_puts[row]) {
      PutEnt& e = puts[pos];
      if (e.alive) {
        e.alive = false;
        put_pos.erase(keyof(e.row, e.cid));
      }
    }
    row_puts[row].clear();
  };
  auto cv_get = [&](int32_t row, int32_t cid) -> int64_t {
    auto it = cvs.find(keyof(row, cid));
    if (it == cvs.end() || it->second.gen != cv_gen[row]) return 0;
    return it->second.cv;
  };

  int32_t spec_n = 0;  // flat write cursor across items
  for (int32_t it_i = 0; it_i < n_items; ++it_i) {
    if (del_off[it_i] > del_off[it_i + 1] ||
        ord_off[it_i] > ord_off[it_i + 1])
      return 2;
    int32_t item_start = spec_n;
    auto emit = [&](int32_t row, int32_t cid, int32_t ord, int64_t cv,
                    int64_t cl) -> int32_t {
      int32_t seq = spec_n - item_start;
      out_spec_row[spec_n] = row;
      out_spec_cid[spec_n] = cid;
      out_spec_ord[spec_n] = ord;
      out_spec_cv[spec_n] = cv;
      out_spec_cl[spec_n] = cl;
      ++spec_n;
      return seq;
    };
    // delete kind first: bumped-EVEN causal lengths (the tombstone
    // parity), row clocks wiped, one sentinel spec per deleted row
    for (int32_t j = del_off[it_i]; j < del_off[it_i + 1]; ++j) {
      int32_t row = del_row[j];
      if (row < 0 || row >= n_rows) return 2;
      int64_t cl = (exists[row] ? cl_live[row] : 1) + 1;
      cl += (cl & 1);
      cl_live[row] = cl;
      exists[row] = 1;
      rows_up_set(row, cl);
      clear_clocks(row);
      int32_t seq = emit(row, FIN_CID_SENTINEL, -1, cl, cl);
      put(row, FIN_CID_SENTINEL, cl, it_i, seq);
    }
    // in-order decision walk over the deduped keys (sequential
    // immediate-effect semantics — see the header comment)
    for (int32_t j = ord_off[it_i]; j < ord_off[it_i + 1]; ++j) {
      int32_t row = ord_row[j], cid = ord_cid[j];
      if (row < 0 || row >= n_rows || cid < FIN_CID_SENTINEL) return 2;
      if (cid == FIN_CID_SENTINEL) {
        // sentinel kind: creation (row unseen) or resurrection (even
        // cl -> next odd); an alive row's sentinel is a no-op
        bool ex = exists[row] != 0;
        int64_t prev = ex ? cl_live[row] : 0;
        int64_t cl = (prev % 2 == 0) ? prev + 1 : prev;
        if (!ex || prev % 2 == 0) {
          cl_live[row] = cl;
          exists[row] = 1;
          rows_up_set(row, cl);
          if (prev % 2 == 0 && prev > 0) clear_clocks(row);
          int32_t seq = emit(row, FIN_CID_SENTINEL, -1, cl, cl);
          put(row, FIN_CID_SENTINEL, cl, it_i, seq);
        }
      } else {
        // column kind: live causal length + bumped col_version
        int64_t cl = exists[row] ? cl_live[row] : 1;
        int64_t cv = cv_get(row, cid) + 1;
        cvs[keyof(row, cid)] = CvEnt{cv, cv_gen[row]};
        int32_t seq = emit(row, cid, j, cv, cl);
        put(row, cid, cv, it_i, seq);
      }
    }
    out_spec_count[it_i] = spec_n - item_start;
  }

  *out_n_up = n_up;
  *out_n_clear = n_clear;
  int32_t n_put = 0;
  for (const PutEnt& e : puts) {
    if (!e.alive) continue;
    out_put_row[n_put] = e.row;
    out_put_cid[n_put] = e.cid;
    out_put_cv[n_put] = e.cv;
    out_put_item[n_put] = e.item;
    out_put_seq[n_put] = e.seq;
    ++n_put;
  }
  *out_n_put = n_put;
  return 0;
}

// Native columnar CRDT merge: the batch decision loop of
// corrosion_tpu/store/crdt.py::_apply_batch (phase B) in C++.
//
// The reference's only native component is the cr-sqlite C extension whose
// merge rules run inside INSERT INTO crsql_changes
// (klukai-agent/src/agent/util.rs:703-1310 drives it); this library is our
// equivalent native CRDT layer for the remote-apply hot path: Python
// bulk-reads the local snapshot (phase A), hands the batch + snapshot to
// `crdt_merge_batch` as columnar arrays, and flushes the returned final
// plans with executemany (phase C).  Semantics are pinned to the Python
// decision loop by tests/test_crdt_batch.py (randomized equivalence across
// per-row / python-batched / native-batched).
//
// Decision rules mirrored exactly (column-level LWW with causal length):
//   ch.cl < local_cl                      -> lose (row-level dominance)
//   ch.cl > local_cl                      -> causal transition: clock rows
//       reset (every transition), data cells reset only on delete (even
//       cl); odd re-create keeps surviving cell values
//   ch.cl == local_cl (odd, non-sentinel) -> col_version compare; equal
//       col_version falls back to "largest value wins" over the current
//       cell value (crsql merge-equal-values)
//
// Value order matches types/values.py::cmp_values bit-for-bit, including
// Python's EXACT mixed int/float comparison (long double on x86-64 has a
// 64-bit mantissa, so int64 values convert exactly).
//
// Build: g++ -O2 -fPIC -shared (see corrosion_tpu/native.py).

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t VT_INTEGER = 1;
constexpr uint8_t VT_REAL = 2;
constexpr uint8_t VT_TEXT = 3;
constexpr uint8_t VT_BLOB = 4;
constexpr uint8_t VT_NULL = 5;

// out_flags bits (must match corrosion_tpu/store/crdt.py native glue)
constexpr uint8_t F_ROWCL = 1;    // row_cl upsert with out_row_cl[pk]
constexpr uint8_t F_CLEARED = 2;  // non-sentinel clock rows drop
constexpr uint8_t F_DELETE = 4;   // data row delete
constexpr uint8_t F_ENSURE = 8;   // data row ensure-exists

struct Value {
  uint8_t type;
  int64_t i;
  double r;
  const uint8_t* p;
  int64_t len;
};

int rank_of(uint8_t t) {
  switch (t) {
    case VT_NULL: return 0;
    case VT_INTEGER:
    case VT_REAL: return 1;
    case VT_TEXT: return 2;
    case VT_BLOB: return 3;
  }
  return 4;
}

// types/values.py::cmp_values: NULL < numeric < TEXT < BLOB; numerics
// compare exactly across int/float like Python (not via lossy double).
int cmp_values(const Value& a, const Value& b) {
  int ra = rank_of(a.type), rb = rank_of(b.type);
  if (ra != rb) return ra < rb ? -1 : 1;
  if (ra == 0) return 0;
  if (ra == 1) {
    if (a.type == VT_INTEGER && b.type == VT_INTEGER)
      return a.i < b.i ? -1 : (a.i > b.i ? 1 : 0);
    if (a.type == VT_REAL && b.type == VT_REAL)
      return a.r < b.r ? -1 : (a.r > b.r ? 1 : 0);
    long double la = a.type == VT_INTEGER ? (long double)a.i : (long double)a.r;
    long double lb = b.type == VT_INTEGER ? (long double)b.i : (long double)b.r;
    return la < lb ? -1 : (la > lb ? 1 : 0);
  }
  int64_t n = a.len < b.len ? a.len : b.len;
  int c = n ? std::memcmp(a.p, b.p, (size_t)n) : 0;
  if (c != 0) return c < 0 ? -1 : 1;
  return a.len < b.len ? -1 : (a.len > b.len ? 1 : 0);
}

struct ClockEnt {
  int64_t cv;
  uint32_t gen;
  int32_t val_idx;  // change index whose value is current, -1 = snapshot
};

struct CellEnt {
  uint32_t gen;
  int32_t idx;  // winning change index (value + clock_entry source)
};

inline uint64_t keyof(int32_t pk, int32_t cid) {
  return ((uint64_t)(uint32_t)pk << 32) | (uint32_t)(cid + 1);
}

}  // namespace

extern "C" int crdt_merge_batch(
    // batch (one table), all arrays length n unless noted
    int32_t n, const int32_t* pk_id, const int32_t* cid_id,  // cid -1 = sentinel
    const int64_t* col_version, const int64_t* cl,
    const uint8_t* val_type, const int64_t* val_int, const double* val_real,
    const int64_t* val_off, const int64_t* val_len, const uint8_t* arena,
    // local snapshot
    int32_t n_pks, const int64_t* local_cl,
    int32_t n_clock, const int32_t* ck_pk, const int32_t* ck_cid,
    const int64_t* ck_cv,
    // prefetched current cell values for tie candidates
    int32_t n_disk, const int32_t* dk_pk, const int32_t* dk_cid,
    const uint8_t* dk_type, const int64_t* dk_int, const double* dk_real,
    const int64_t* dk_off, const int64_t* dk_len, const uint8_t* dk_arena,
    // outputs
    uint8_t* win,                               // [n]
    int64_t* out_row_cl, uint8_t* out_flags,    // [n_pks]
    int32_t* out_sentinel_idx,                  // [n_pks], -1 = none
    int32_t* out_cell_pk, int32_t* out_cell_cid, int32_t* out_cell_idx,
    int32_t* out_n_cells,                       // cell plans, capacity n
    int32_t* out_clock_pk, int32_t* out_clock_cid, int32_t* out_clock_idx,
    int32_t* out_n_clocks) {                    // clock plans, capacity n
  if (n < 0 || n_pks < 0 || n_clock < 0 || n_disk < 0) return 2;

  std::vector<int64_t> cur_cl(local_cl, local_cl + n_pks);
  std::vector<uint32_t> clock_gen(n_pks, 0), cell_gen(n_pks, 0);

  std::unordered_map<uint64_t, ClockEnt> clock;
  clock.reserve((size_t)(n_clock + n) * 2);
  for (int32_t i = 0; i < n_clock; ++i) {
    if (ck_pk[i] < 0 || ck_pk[i] >= n_pks) return 2;
    clock[keyof(ck_pk[i], ck_cid[i])] = ClockEnt{ck_cv[i], 0, -1};
  }
  std::unordered_map<uint64_t, int32_t> disk;
  disk.reserve((size_t)n_disk * 2);
  for (int32_t i = 0; i < n_disk; ++i) {
    if (dk_pk[i] < 0 || dk_pk[i] >= n_pks) return 2;
    disk[keyof(dk_pk[i], dk_cid[i])] = i;
  }
  std::unordered_map<uint64_t, CellEnt> cells;
  cells.reserve((size_t)n * 2);

  for (int32_t i = 0; i < n_pks; ++i) out_sentinel_idx[i] = -1;
  std::memset(out_flags, 0, (size_t)n_pks);
  std::memset(win, 0, (size_t)n);

  auto change_val = [&](int32_t i) -> Value {
    return Value{val_type[i], val_int[i], val_real[i],
                 arena + val_off[i], val_len[i]};
  };

  for (int32_t i = 0; i < n; ++i) {
    int32_t pk = pk_id[i];
    if (pk < 0 || pk >= n_pks) return 2;
    int32_t cid = cid_id[i];
    int64_t lcl = cur_cl[pk];
    int64_t ccl = cl[i];
    if (ccl < lcl) continue;
    bool w = false;
    if (ccl > lcl) {
      cur_cl[pk] = ccl;
      out_row_cl[pk] = ccl;
      out_flags[pk] |= F_ROWCL | F_CLEARED;
      clock_gen[pk]++;  // every transition resets clock rows + plans
      out_sentinel_idx[pk] = i;
      if ((ccl & 1) == 0) {
        cell_gen[pk]++;  // delete: pending cell writes die with the row
        out_flags[pk] |= F_DELETE;
        out_flags[pk] &= ~F_ENSURE;
        w = true;
      } else {
        out_flags[pk] |= F_ENSURE;
        if (cid >= 0) {
          clock[keyof(pk, cid)] =
              ClockEnt{col_version[i], clock_gen[pk], i};
          cells[keyof(pk, cid)] = CellEnt{cell_gen[pk], i};
        }
        w = true;
      }
    } else {
      if ((lcl & 1) == 0 || cid < 0) continue;
      auto it = clock.find(keyof(pk, cid));
      bool present = it != clock.end() && it->second.gen == clock_gen[pk];
      int64_t lcv = present ? it->second.cv : 0;
      if (col_version[i] < lcv) continue;
      if (col_version[i] == lcv && present) {
        // lazily-marshaled values: type 0 = not encoded; the Python glue
        // only skips values provably never compared, so hitting one means
        // fall back to the reference loop rather than guess
        if (val_type[i] == 0) return 1;
        Value cur;
        auto cit = cells.find(keyof(pk, cid));
        if (cit != cells.end() && cit->second.gen == cell_gen[pk]) {
          if (val_type[cit->second.idx] == 0) return 1;
          cur = change_val(cit->second.idx);
        } else {
          auto dit = disk.find(keyof(pk, cid));
          if (dit == disk.end()) return 1;  // caller falls back to Python
          int32_t d = dit->second;
          cur = Value{dk_type[d], dk_int[d], dk_real[d],
                      dk_arena + dk_off[d], dk_len[d]};
        }
        if (cmp_values(change_val(i), cur) <= 0) continue;
      }
      out_flags[pk] |= F_ENSURE;
      cells[keyof(pk, cid)] = CellEnt{cell_gen[pk], i};
      clock[keyof(pk, cid)] = ClockEnt{col_version[i], clock_gen[pk], i};
      w = true;
    }
    if (w) win[i] = 1;
  }

  // emit surviving plans; (pk, cid) recovered from the map keys
  int32_t nc = 0;
  for (const auto& kv : cells) {
    int32_t pk = (int32_t)(kv.first >> 32);
    if (kv.second.gen != cell_gen[pk]) continue;
    out_cell_pk[nc] = pk;
    out_cell_cid[nc] = (int32_t)(kv.first & 0xffffffffu) - 1;
    out_cell_idx[nc] = kv.second.idx;
    ++nc;
  }
  *out_n_cells = nc;
  int32_t nk = 0;
  for (const auto& kv : clock) {
    int32_t pk = (int32_t)(kv.first >> 32);
    if (kv.second.val_idx < 0 || kv.second.gen != clock_gen[pk]) continue;
    out_clock_pk[nk] = pk;
    out_clock_cid[nk] = (int32_t)(kv.first & 0xffffffffu) - 1;
    out_clock_idx[nk] = kv.second.val_idx;
    ++nk;
  }
  *out_n_clocks = nk;
  return 0;
}

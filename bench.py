"""Benchmark: time-to-stable-membership for a simulated SWIM devcluster.

North star (BASELINE.md): converge a 100k-member devcluster to stable
membership in <60 s on a v5e-8.  This single-chip bench measures wall-clock
to 99.9% live-member coverage for BENCH_N members (default 10_000 — the
"10k on one core" rung of the BASELINE.json scale ladder) with zero false
positives, and reports vs_baseline as (60 s budget / measured), >1 = faster
than the north-star budget.

Prints exactly one JSON line on stdout.

Driver hardening (round 2): the TPU plugin in the driver image can hang or
fail at backend init (see corrosion_tpu/runtime/jaxenv.py).  The parent
process therefore does no jax work at all: it probes the inherited backend
in a bounded subprocess, then runs the measured simulation in a child with
a wall-clock budget, falling back to a known-good CPU env (plugin stripped
from PYTHONPATH) if the TPU attempt probes bad, crashes, or times out.
Every phase is bounded so the driver can never hit rc=124 here.

Env knobs: BENCH_N, BENCH_COVERAGE, BENCH_BUDGET_S (total wall budget,
default 1500), BENCH_PROBE_S (TPU probe bound, default 150),
BENCH_FORCE_CPU=1 (skip the TPU attempt).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

_CHILD_FLAG = "CORRO_BENCH_CHILD"

# The measured code surface: kernel + simulation driver.  Fingerprinted
# into every bench record so a replayed TPU measurement can be checked
# against the code actually in the tree at replay time.
_MEASURED_FILES = (
    "corrosion_tpu/ops/swim.py",
    "corrosion_tpu/ops/inbox_pallas.py",
    "corrosion_tpu/models/cluster.py",
)


def _code_fingerprint() -> dict:
    import hashlib

    root = os.path.dirname(os.path.abspath(__file__))
    out = {}
    for rel in _MEASURED_FILES:
        try:
            with open(os.path.join(root, rel), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()[:12]
        except OSError:
            out[rel] = "missing"
    return out


def child_main() -> None:
    """The measured simulation; runs under an env chosen by the parent."""
    # fingerprint BEFORE the (potentially tens-of-minutes) run: the sha
    # must describe the code actually imported and measured, not whatever
    # the tree holds by the time the result prints
    code_sha = _code_fingerprint()
    jaxenv.enable_compilation_cache()
    import jax

    from corrosion_tpu.models.cluster import ClusterSim

    n = int(os.environ.get("BENCH_N", "10000"))
    target = float(os.environ.get("BENCH_COVERAGE", "0.999"))
    # Feed bandwidth W = fe*F entries pulled per member per tick sized at
    # ~n/4: convergence needs ~log2(n) spaced visits per subject, i.e.
    # ticks ≈ log2(n) * n/W + gossip floor (measured: 150 ticks at n=10k).
    # Few LARGE windows beat many small ones — same pulled volume, fewer
    # slice dispatches (r3 profile, PROFILE.md).
    feeds = max(1, int(os.environ.get("BENCH_FEEDS", "4")))
    fe = max(25, n // (4 * feeds))
    # boot-convergence-tuned gossip widths: during a mass boot the feed
    # carries the bulk transfer, so trimmed gossip/probe widths shave
    # ~20% off the tick without changing the tick count (measured sweep
    # at n=10k, PROFILE.md)
    params = dict(
        feeds_per_tick=feeds,
        feed_entries=fe,
        piggyback=4,
        incoming_slots=8,
        buffer_slots=12,
        probe_candidates=2,
        antientropy=1,
    )
    # inbox build dispatch (sort | gsort | pallas): the r4 on-chip phase
    # table showed the flat sort beating the grouped form on the TPU
    # (the CPU ordering is reversed) — this knob lets the hunter battery
    # A/B the whole-bench effect on the real chip
    impl = os.environ.get("BENCH_INBOX_IMPL")
    if impl:
        params["inbox_impl"] = impl
    # gossip target selection (pick | shift): "shift" replaces the
    # sort-based inbox with exact row-gather delivery — on CPU it both
    # converges in fewer ticks (better mixing at mass boot) and more
    # than halves the tick (n=4000: 19.0 s -> 7.7 s, stable_tick 60 ->
    # 50); the battery A/Bs it on chip
    gmode = os.environ.get("BENCH_GOSSIP_MODE")
    if gmode:
        params["gossip_mode"] = gmode

    # Bootstrap topology: Chord-style finger list (power-of-two offsets,
    # swim.finger_offsets — log2(n) configured addresses per node, a modest
    # deployment choice: 14 entries at 10k). The expander bootstrap graph
    # gives feed-partner picks long-range reach from tick 0; measured at
    # n=10k it converges in ~70 ticks vs ~161 for a 3-neighbor ring
    # (PROFILE.md — the early epidemic was ring-partner-correlation
    # bound, not bandwidth bound).
    seed_mode = os.environ.get("BENCH_SEED_MODE", "fingers")

    # 25-tick cadence fits the ~70-tick finger-bootstrap convergence
    # (worst-case overshoot 24 ticks; stats are ~1 s each on CPU)
    record_every = int(os.environ.get("BENCH_RECORD_EVERY", "25"))
    # device-resident convergence loop (lax.while_loop of tick scans
    # with an on-device coverage predicate): zero host round-trips in
    # the measured window — each host-side stats check costs a full
    # tunnel RTT (~85 ms measured), comparable to ~10 ticks at n=10k
    device_loop = os.environ.get("BENCH_DEVICE_LOOP", "1") != "0"
    check_every = max(1, int(os.environ.get("BENCH_CHECK_EVERY", "5")))
    max_ticks = 5000
    # compile warm-up on a THROWAWAY sim (same shapes/static args), so the
    # measured cluster starts cold at tick 0 — warming up the real state
    # would advance convergence before the clock starts
    warm = ClusterSim(n, seed=1, seed_mode=seed_mode, **params)
    if device_loop:
        # must precede step(): the loop's tick-limit static arg is
        # ticks+max_ticks and has to match the measured call's
        warm.warm_device_loop(target, max_ticks, check_every)
    warm.step(record_every)
    warm.step(10)  # the fine-phase chunk compiles too
    warm.stats()
    del warm

    sim = ClusterSim(n, seed=0, seed_mode=seed_mode, **params)
    jax.block_until_ready(sim.state.view)

    t0 = time.monotonic()
    if device_loop:
        stable_tick = sim.run_until_stable_device(
            coverage_target=target,
            max_ticks=max_ticks,
            check_every=check_every,
        )
    else:
        stable_tick = sim.run_until_stable(
            coverage_target=target,
            max_ticks=max_ticks,
            record_every=record_every,
            fine_every=10,
        )
    elapsed = time.monotonic() - t0
    stats = sim.stats()

    budget = 60.0
    print(
        json.dumps(
            {
                "metric": f"time_to_stable_membership_n{n}",
                "value": round(elapsed, 3),
                "unit": "s",
                "vs_baseline": round(budget / elapsed, 3) if elapsed > 0 else 0.0,
                "detail": {
                    "n_members": n,
                    "coverage": round(stats["coverage"], 5),
                    "false_positive": round(stats["false_positive"], 6),
                    "stable_tick": stable_tick,
                    "feeds_per_tick": feeds,
                    "feed_entries": fe,
                    "seed_mode": seed_mode,
                    "record_every": record_every,
                    "coverage_target": target,
                    "inbox_impl": sim.params.inbox_impl,
                    "gossip_mode": sim.params.gossip_mode,
                    "device_loop": device_loop,
                    "check_every": check_every if device_loop else None,
                    "code_sha": code_sha,
                    "measured_at": time.strftime(
                        "%Y-%m-%d %H:%M:%S", time.gmtime()
                    ),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )
    if stable_tick is None:
        sys.exit(1)


def _run_child(env: dict, timeout: float) -> tuple[dict | None, int]:
    """Run the bench child under ``env``; (parsed JSON line, returncode).

    The JSON is parsed even when the child exits nonzero: a measured
    convergence failure still carries its diagnostics (coverage,
    false_positive, stable_tick) and must not be discarded.
    """
    env = dict(env)
    env[_CHILD_FLAG] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, -1
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed, proc.returncode
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:])
    return None, proc.returncode


def _banked_record_path(n: int) -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"BENCH_TPU_{n // 1000}k.json",
    )


def _stored_tpu_record(n: int) -> tuple[dict | None, str | None]:
    """Load this round's measured-on-TPU bench record for ``n``, if any.

    The round-start hunter battery (scripts/tpu_hunter.py) runs bench.py
    on the real chip while the tunnel is alive and tees the JSON line to
    BENCH_TPU_<n//1000>k.json.  If the tunnel is wedged again by the time
    the driver runs this script (the r3 failure mode: up ~10 min at round
    start, dead for the next 10+ h), that stored measurement is a more
    honest headline than a CPU wall-clock — PROVIDED it measured the same
    workload.  Guards:

    - the stored record must match the requested config (n, seed mode,
      feeds, record cadence, coverage target) as derived from the same
      env vars the child uses; any mismatch disqualifies it;
    - the measured-code fingerprint is recomputed at replay time and the
      record is REJECTED unless it carries a fingerprint that matches the
      tree exactly (r4 verdict: a TPU-labeled headline must be tied to a
      code version — a sha-less or drifted record is evidence about some
      other kernel, so the live number, even CPU, is the honest one);
    - the caller never substitutes it for a live MEASURED convergence
      failure — only for runs that could not reach the chip at all.

    Returns ``(record, None)`` on success or ``(None, reason)`` where
    ``reason`` explains the rejection for the attempts provenance.
    """
    path = _banked_record_path(n)
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None, None
    feeds = max(1, int(os.environ.get("BENCH_FEEDS", "4")))
    want = {
        "n_members": n,
        "seed_mode": os.environ.get("BENCH_SEED_MODE", "fingers"),
        "feeds_per_tick": feeds,
        "record_every": int(os.environ.get("BENCH_RECORD_EVERY", "25")),
    }
    want_target = float(os.environ.get("BENCH_COVERAGE", "0.999"))
    for line in text.splitlines():
        try:
            parsed = json.loads(line)
        except (ValueError, TypeError):
            continue
        if not (
            isinstance(parsed, dict)
            and "metric" in parsed
            and parsed.get("detail", {}).get("platform") == "tpu"
        ):
            continue
        det = parsed["detail"]
        if any(det.get(k) != v for k, v in want.items()):
            # measured a different workload: not replayable
            return None, "replay-rejected:workload-mismatch"
        if "coverage_target" in det and det["coverage_target"] != want_target:
            return None, "replay-rejected:coverage-target-mismatch"
        if det.get("inbox_impl", "gsort") != os.environ.get(
            "BENCH_INBOX_IMPL", "gsort"
        ):
            return None, "replay-rejected:inbox-impl-mismatch"
        # stored records without the field predate the knob (pick era);
        # the env default must track the kernel's CURRENT default
        # ("shift" since the r5 flip) so a replay always describes what
        # a live run would measure
        if det.get("gossip_mode", "pick") != os.environ.get(
            "BENCH_GOSSIP_MODE", "shift"
        ):
            return None, "replay-rejected:gossip-mode-mismatch"
        if det.get("stable_tick") is None:
            # stored record itself is a convergence failure
            return None, "replay-rejected:stored-convergence-failure"
        if "measured_at" not in det:
            return None, "replay-rejected:measured-at-missing"
        stored_sha = det.get("code_sha")
        now_sha = _code_fingerprint()
        if stored_sha is None:
            return None, "replay-rejected:code-sha-missing"
        drift = sorted(
            f for f in set(stored_sha) | set(now_sha)
            if stored_sha.get(f) != now_sha.get(f)
        )
        if drift:
            return None, "replay-rejected:code-drift:" + ",".join(drift)
        det["replayed_from"] = {
            "file": os.path.basename(path),
            # always present: code_sha and measured_at are stamped
            # together at capture, and sha-less records were rejected
            "measured_at": det["measured_at"],
        }
        return parsed, None
    return None, None


def main() -> None:
    t_start = time.monotonic()
    total_budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    probe_budget = float(os.environ.get("BENCH_PROBE_S", "150"))

    def remaining() -> float:
        return max(30.0, total_budget - (time.monotonic() - t_start))

    attempts: list[str] = []
    result: dict | None = None
    rc = 0

    # Attempt 1: the inherited backend (real TPU when the tunnel is up),
    # but only if a bounded probe proves it can initialize.
    if os.environ.get("BENCH_FORCE_CPU") != "1" and os.environ.get(
        "JAX_PLATFORMS", ""
    ) not in ("cpu",):
        platform = jaxenv.probe(None, probe_budget)
        if platform and platform != "cpu":
            attempts.append(platform)
            # leave headroom for the CPU fallback attempt
            result, rc = _run_child(os.environ.copy(), remaining() * 0.6)

    # Attempt 2 (fallback): known-good CPU env, plugin stripped. Only when
    # attempt 1 produced no measurement at all — a measured
    # convergence failure is a result, not a reason to re-run.
    if result is None:
        attempts.append("cpu-fallback")
        result, rc = _run_child(jaxenv.stripped_env(), remaining())

    # The live attempt could not reach the chip: fall back to this
    # round's measured-on-TPU record when one exists for the same
    # workload, demoting the live CPU result to provenance.  A live
    # MEASURED convergence failure (rc != 0 with a parsed result) is
    # never replaced — that is a result about the current code, and
    # hiding it behind an older green record would mask a regression.
    live_measured_failure = result is not None and rc != 0
    # An explicitly forced CPU run is a request for a CPU number (the
    # baseline-ladder refresh path) — never substitute the TPU record.
    forced_cpu = os.environ.get("BENCH_FORCE_CPU") == "1" or os.environ.get(
        "JAX_PLATFORMS", ""
    ) in ("cpu",)
    if not live_measured_failure and not forced_cpu and (
        result is None or result.get("detail", {}).get("platform") != "tpu"
    ):
        n = int(os.environ.get("BENCH_N", "10000"))
        stored, reject_reason = _stored_tpu_record(n)
        if reject_reason is not None:
            attempts.append(reject_reason)
        if stored is not None:
            attempts.append("tpu-replay")
            if result is not None:
                stored["detail"]["live_fallback"] = dict(
                    result.get("detail", {}),
                    value=result.get("value"),
                )
            result, rc = stored, 0

    if result is None:
        print(
            json.dumps(
                {
                    "metric": "time_to_stable_membership",
                    "value": 0.0,
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "error": "all bench attempts failed or timed out",
                    "attempts": attempts,
                }
            )
        )
        sys.exit(1)

    result.setdefault("detail", {})["attempts"] = attempts
    print(json.dumps(result))
    if rc != 0:
        sys.exit(1)


if __name__ == "__main__":
    if os.environ.get(_CHILD_FLAG) == "1":
        child_main()
    else:
        main()

"""Benchmark: time-to-stable-membership for a simulated SWIM devcluster.

North star (BASELINE.md): converge a 100k-member devcluster to stable
membership in <60 s on a v5e-8. This single-chip bench measures wall-clock
to 99.9% live-member coverage for BENCH_N members (default 10_000 — the
"10k on one core" rung of the BASELINE.json scale ladder) with zero false
positives, and reports vs_baseline as (60 s budget / measured), >1 = faster
than the north-star budget.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax

    from corrosion_tpu.models.cluster import ClusterSim

    n = int(os.environ.get("BENCH_N", "10000"))
    target = float(os.environ.get("BENCH_COVERAGE", "0.999"))
    # feed rate sized so convergence lands in O(100) ticks at any n
    feeds = max(4, n // (25 * 50))

    sim = ClusterSim(n, seed=0, feeds_per_tick=feeds)
    # warm-up/compile outside the measured window
    sim.step()
    jax.block_until_ready(sim.state.view)

    t0 = time.monotonic()
    stable_tick = sim.run_until_stable(
        coverage_target=target, max_ticks=5000, record_every=5
    )
    elapsed = time.monotonic() - t0
    stats = sim.stats()

    budget = 60.0
    print(
        json.dumps(
            {
                "metric": f"time_to_stable_membership_n{n}",
                "value": round(elapsed, 3),
                "unit": "s",
                "vs_baseline": round(budget / elapsed, 3) if elapsed > 0 else 0.0,
                "detail": {
                    "n_members": n,
                    "coverage": round(stats["coverage"], 5),
                    "false_positive": round(stats["false_positive"], 6),
                    "stable_tick": stable_tick,
                    "feeds_per_tick": feeds,
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )
    if stable_tick is None:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Benchmark: time-to-stable-membership for a simulated SWIM devcluster.

North star (BASELINE.md): converge a 100k-member devcluster to stable
membership in <60 s on a v5e-8.  This single-chip bench measures wall-clock
to 99.9% live-member coverage for BENCH_N members (default 10_000 — the
"10k on one core" rung of the BASELINE.json scale ladder) with zero false
positives, and reports vs_baseline as (60 s budget / measured), >1 = faster
than the north-star budget.

Prints exactly one JSON line on stdout.

Driver hardening (round 2): the TPU plugin in the driver image can hang or
fail at backend init (see corrosion_tpu/runtime/jaxenv.py).  The parent
process therefore does no jax work at all: it probes the inherited backend
in a bounded subprocess, then runs the measured simulation in a child with
a wall-clock budget, falling back to a known-good CPU env (plugin stripped
from PYTHONPATH) if the TPU attempt probes bad, crashes, or times out.
Every phase is bounded so the driver can never hit rc=124 here.

Env knobs: BENCH_N, BENCH_COVERAGE, BENCH_BUDGET_S (total wall budget,
default 1500), BENCH_PROBE_S (TPU probe bound, default 150),
BENCH_FORCE_CPU=1 (skip the TPU attempt).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from corrosion_tpu.runtime import jaxenv  # noqa: E402

_CHILD_FLAG = "CORRO_BENCH_CHILD"


def child_main() -> None:
    """The measured simulation; runs under an env chosen by the parent."""
    jaxenv.enable_compilation_cache()
    import jax

    from corrosion_tpu.models.cluster import ClusterSim

    n = int(os.environ.get("BENCH_N", "10000"))
    target = float(os.environ.get("BENCH_COVERAGE", "0.999"))
    # Feed bandwidth W = fe*F entries pulled per member per tick sized at
    # ~n/4: convergence needs ~log2(n) spaced visits per subject, i.e.
    # ticks ≈ log2(n) * n/W + gossip floor (measured: 150 ticks at n=10k).
    # Few LARGE windows beat many small ones — same pulled volume, fewer
    # slice dispatches (r3 profile, PROFILE.md).
    feeds = max(1, int(os.environ.get("BENCH_FEEDS", "4")))
    fe = max(25, n // (4 * feeds))
    # boot-convergence-tuned gossip widths: during a mass boot the feed
    # carries the bulk transfer, so trimmed gossip/probe widths shave
    # ~20% off the tick without changing the tick count (measured sweep
    # at n=10k, PROFILE.md)
    params = dict(
        feeds_per_tick=feeds,
        feed_entries=fe,
        piggyback=4,
        incoming_slots=8,
        buffer_slots=12,
        probe_candidates=2,
        antientropy=1,
    )

    # Bootstrap topology: Chord-style finger list (power-of-two offsets,
    # swim.finger_offsets — log2(n) configured addresses per node, a modest
    # deployment choice: 14 entries at 10k). The expander bootstrap graph
    # gives feed-partner picks long-range reach from tick 0; measured at
    # n=10k it converges in ~70 ticks vs ~161 for a 3-neighbor ring
    # (PROFILE.md — the early epidemic was ring-partner-correlation
    # bound, not bandwidth bound).
    seed_mode = os.environ.get("BENCH_SEED_MODE", "fingers")

    # 25-tick cadence fits the ~70-tick finger-bootstrap convergence
    # (worst-case overshoot 24 ticks; stats are ~1 s each on CPU)
    record_every = int(os.environ.get("BENCH_RECORD_EVERY", "25"))
    # compile warm-up on a THROWAWAY sim (same shapes/static args), so the
    # measured cluster starts cold at tick 0 — warming up the real state
    # would advance convergence before the clock starts
    warm = ClusterSim(n, seed=1, seed_mode=seed_mode, **params)
    warm.step(record_every)
    warm.step(10)  # the fine-phase chunk compiles too
    warm.stats()
    del warm

    sim = ClusterSim(n, seed=0, seed_mode=seed_mode, **params)
    jax.block_until_ready(sim.state.view)

    t0 = time.monotonic()
    stable_tick = sim.run_until_stable(
        coverage_target=target,
        max_ticks=5000,
        record_every=record_every,
        fine_every=10,
    )
    elapsed = time.monotonic() - t0
    stats = sim.stats()

    budget = 60.0
    print(
        json.dumps(
            {
                "metric": f"time_to_stable_membership_n{n}",
                "value": round(elapsed, 3),
                "unit": "s",
                "vs_baseline": round(budget / elapsed, 3) if elapsed > 0 else 0.0,
                "detail": {
                    "n_members": n,
                    "coverage": round(stats["coverage"], 5),
                    "false_positive": round(stats["false_positive"], 6),
                    "stable_tick": stable_tick,
                    "feeds_per_tick": feeds,
                    "feed_entries": fe,
                    "seed_mode": seed_mode,
                    "record_every": record_every,
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )
    if stable_tick is None:
        sys.exit(1)


def _run_child(env: dict, timeout: float) -> tuple[dict | None, int]:
    """Run the bench child under ``env``; (parsed JSON line, returncode).

    The JSON is parsed even when the child exits nonzero: a measured
    convergence failure still carries its diagnostics (coverage,
    false_positive, stable_tick) and must not be discarded.
    """
    env = dict(env)
    env[_CHILD_FLAG] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, -1
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed, proc.returncode
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:])
    return None, proc.returncode


def main() -> None:
    t_start = time.monotonic()
    total_budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    probe_budget = float(os.environ.get("BENCH_PROBE_S", "150"))

    def remaining() -> float:
        return max(30.0, total_budget - (time.monotonic() - t_start))

    attempts: list[str] = []
    result: dict | None = None
    rc = 0

    # Attempt 1: the inherited backend (real TPU when the tunnel is up),
    # but only if a bounded probe proves it can initialize.
    if os.environ.get("BENCH_FORCE_CPU") != "1" and os.environ.get(
        "JAX_PLATFORMS", ""
    ) not in ("cpu",):
        platform = jaxenv.probe(None, probe_budget)
        if platform and platform != "cpu":
            attempts.append(platform)
            # leave headroom for the CPU fallback attempt
            result, rc = _run_child(os.environ.copy(), remaining() * 0.6)

    # Attempt 2 (fallback): known-good CPU env, plugin stripped. Only when
    # attempt 1 produced no measurement at all — a measured
    # convergence failure is a result, not a reason to re-run.
    if result is None:
        attempts.append("cpu-fallback")
        result, rc = _run_child(jaxenv.stripped_env(), remaining())

    if result is None:
        print(
            json.dumps(
                {
                    "metric": "time_to_stable_membership",
                    "value": 0.0,
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "error": "all bench attempts failed or timed out",
                    "attempts": attempts,
                }
            )
        )
        sys.exit(1)

    result.setdefault("detail", {})["attempts"] = attempts
    print(json.dumps(result))
    if rc != 0:
        sys.exit(1)


if __name__ == "__main__":
    if os.environ.get(_CHILD_FLAG) == "1":
        child_main()
    else:
        main()
